"""Fleet facade (reference distributed/fleet/base/fleet_base.py:125,572,937).

fleet.init → role discovery + mesh setup; distributed_optimizer wraps the
user optimizer with the meta-optimizer stack chosen from DistributedStrategy
(reference base/strategy_compiler.py); minimize rewrites the program for the
selected parallelism and returns ops the TPU executor understands.
"""
from __future__ import annotations

from .distributed_strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase

__all__ = ["Fleet", "init", "distributed_optimizer", "minimize"]


class Fleet:
    def __init__(self):
        self._role_maker: RoleMakerBase | None = None
        self._strategy: DistributedStrategy | None = None
        self._user_optimizer = None
        self._is_collective = True

    # -- lifecycle ----------------------------------------------------------
    def init(self, role_maker=None, is_collective=False, strategy=None):
        from ...env import init_parallel_env
        self._is_collective = is_collective or role_maker is None
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=True)
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        return self

    def is_first_worker(self):
        return self._rm().is_first_worker()

    def worker_index(self):
        return self._rm().worker_index()

    def worker_num(self):
        return self._rm().worker_num()

    def is_worker(self):
        return self._rm().is_worker()

    def worker_endpoints(self, to_string=False):
        eps = self._rm().get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return self._rm().server_num()

    def server_index(self):
        return self._rm().server_index()

    def server_endpoints(self, to_string=False):
        eps = self._rm().get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return self._rm().is_server()

    def barrier_worker(self):
        self._rm()._barrier()

    def _runtime(self):
        if getattr(self, "_ps_runtime", None) is None:
            from ..runtime.parameter_server_runtime import \
                ParameterServerRuntime
            self._ps_runtime = ParameterServerRuntime(self._rm())
        return self._ps_runtime

    def init_worker(self):
        """Connect the worker-side PSClient to all server endpoints."""
        return self._runtime().init_worker()

    def init_server(self, *args, **kwargs):
        self._runtime().init_server(*args, **kwargs)

    def run_server(self, block: bool = True):
        """Serve this shard. Blocks like the reference's run_server unless
        block=False (in-process tests)."""
        return self._runtime().run_server(block=block)

    def stop_worker(self):
        self._runtime().stop_worker()

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ....fluid import io
        return io.save_inference_model(dirname, feeded_var_names,
                                       target_vars, executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ....fluid import io
        return io.save_persistables(executor, dirname, main_program)

    # -- optimization --------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._user_optimizer = optimizer
        if strategy is not None:
            self._strategy = strategy
        return self

    def distributed_model(self, model):
        from ...parallel import DataParallel
        return DataParallel(model)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..meta_optimizers import apply_meta_optimizers
        opt = apply_meta_optimizers(self._user_optimizer, self._strategy,
                                    self._rm())
        res = opt.minimize(loss, startup_program, parameter_list, no_grad_set)
        loss.block.program._sharding_info = _sharding_info_from_strategy(
            self._strategy)
        return res

    @property
    def user_defined_optimizer(self):
        return self._user_optimizer

    def _rm(self) -> RoleMakerBase:
        if self._role_maker is None:
            self.init()
        return self._role_maker

    # dygraph helpers
    def get_loss_scaling(self):
        return None

    def hybrid_train_step(self, cfg, **kwargs):
        """Build the dp x pp x tp functional train step from this fleet's
        strategy (`hybrid_configs` + pipeline/tensor_parallel flags) — the
        consumer of `strategy.pipeline`/`tensor_parallel` for Layer-free GPT
        training (reference chain: fleet pipeline meta-optimizer
        meta_optimizers/pipeline_optimizer.py:24)."""
        from ....parallel.hybrid import HybridParallelTrainStep
        st = self._strategy or DistributedStrategy()
        hc = st.hybrid_configs
        dp, pp, tp = hc["dp_degree"], hc["pp_degree"], hc["mp_degree"]
        if st.tensor_parallel and tp == 1:
            tp = st.tensor_parallel_configs["tensor_parallel_degree"]
        sp = hc.get("sp_degree", 1)
        if st.sequence_parallel and sp == 1:
            sp = st.sequence_parallel_configs["sp_degree"]
        kwargs.setdefault("sp", sp)
        micro = hc["micro_batches"]
        if st.pipeline and micro is None:
            # accumulate_steps defaults to 1 in the strategy bag; only an
            # explicit >1 value is a microbatch count (1 would deadlock the
            # pipeline — HybridParallelTrainStep's 2*pp default is safe)
            acc = st.pipeline_configs.get("accumulate_steps") or 0
            micro = acc if acc > 1 else None
        kwargs.setdefault("n_microbatches", micro)
        kwargs.setdefault("pipeline_schedule",
                          st.pipeline_configs.get("schedule_mode", "1F1B"))
        ep = hc.get("ep_degree", 1)
        if st.expert_parallel and ep == 1:
            ep = st.expert_parallel_configs["ep_degree"]
        kwargs.setdefault("ep", ep)
        kwargs.setdefault("sharding", bool(st.sharding))  # ZeRO-1
        return HybridParallelTrainStep(cfg, dp=dp, pp=pp, tp=tp, **kwargs)


def _sharding_info_from_strategy(strategy: DistributedStrategy) -> dict:
    info = {"mode": "dp"}
    if strategy.tensor_parallel:
        info["tp"] = strategy.tensor_parallel_configs[
            "tensor_parallel_degree"]
        info["tp_rules"] = list(
            strategy.tensor_parallel_configs.get("sharding_rules") or [])
    if strategy.pipeline:
        info["pp"] = strategy.pipeline_configs
    if strategy.sequence_parallel:
        info["sp"] = strategy.sequence_parallel_configs["sp_degree"]
    return info


_fleet = Fleet()


def init(role_maker=None, is_collective=False, strategy=None):
    return _fleet.init(role_maker, is_collective, strategy)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet.distributed_optimizer(optimizer, strategy)


def minimize(loss, **kw):
    return _fleet.minimize(loss, **kw)


is_first_worker = _fleet.is_first_worker
worker_index = _fleet.worker_index
worker_num = _fleet.worker_num
is_worker = _fleet.is_worker
worker_endpoints = _fleet.worker_endpoints
server_num = _fleet.server_num
server_index = _fleet.server_index
server_endpoints = _fleet.server_endpoints
is_server = _fleet.is_server
barrier_worker = _fleet.barrier_worker
init_worker = _fleet.init_worker
init_server = _fleet.init_server
run_server = _fleet.run_server
stop_worker = _fleet.stop_worker
