"""Role makers (reference distributed/fleet/base/role_maker.py).

Reads PADDLE_* env set by the launcher; rendezvous is jax.distributed's
coordinator (replacing the Gloo HTTP/file store)."""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = ["127.0.0.1:6170"]
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return len(self._worker_endpoints)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def _barrier(self, comm_world=None):
        from ...collective import barrier
        barrier()

    def _generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = eps.split(",") if eps else ["127.0.0.1:6170"]
        seps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = seps.split(",") if seps else []
        self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        if self._role == Role.SERVER:
            self._current_id = int(os.environ.get("PADDLE_PORT_INDEX", "0"))


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, worker_endpoints=None, **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._server_endpoints = server_endpoints or []
        self._worker_endpoints = worker_endpoints or \
            [f"127.0.0.1:{6170 + i}" for i in range(worker_num)]
