"""fleetrun — `python -m paddle_tpu.distributed.fleet.launch`.

Reference python/paddle/distributed/fleet/launch.py (console entry
`fleetrun`, python/setup.py.in:505): same engine as
paddle_tpu.distributed.launch, with --servers/--workers parameter-server
mode as the first-class interface.
"""
from ..launch import launch, main

__all__ = ["launch", "main"]

if __name__ == "__main__":
    main()
