"""Fault-injection harness for the PS/heter RPC transport.

Env-driven (all optional; the transport pays one attribute check per
frame when nothing is configured):

  PADDLE_PS_FAULT_DROP=p        drop an outgoing frame with prob p — the
                                socket is closed instead, so the peer
                                sees EOF and the client retry path runs
  PADDLE_PS_FAULT_DELAY=sec     sleep before every send (latency)
  PADDLE_PS_FAULT_TRUNCATE=p    send only the first half of a frame,
                                then close the connection
  PADDLE_PS_FAULT_CORRUPT=p     flip bytes inside the frame BODY (the
                                header's length field stays intact so
                                the peer reads a full frame and the CRC
                                check rejects it — corrupting the length
                                would model a hung peer, not a bad one)
  PADDLE_PS_FAULT_KILL_AFTER=N  server: os._exit after N handled
                                requests
  PADDLE_PS_FAULT_KILL_AFTER_BYTES=N  checkpoint writer: os._exit once
                                N payload bytes have been written
                                (kill-mid-save crash tests)
  PADDLE_PS_FAULT_KILL_AT_STEP=N  trainer: os._exit at the START of
                                training step N (elastic.note_step is
                                the hook) — the deterministic SIGKILL
                                for gang-restart chaos drills
  PADDLE_PS_FAULT_KILL_POINT=recv|reply   kill before dispatch (request
                                lost) or after commit-before-reply (the
                                hard exactly-once case); default reply
  PADDLE_PS_FAULT_STALL=sec     hang injection: sleep this long at the
                                stall point (a wedged-not-dead tier —
                                what the observability watchdog must
                                catch; the in-flight op pins the tier
                                non-idle while its progress counter
                                freezes)
  PADDLE_PS_FAULT_STALL_POINT=dispatch|serving_decode|trainer_step
                                where to stall: the PS server's
                                dispatch path, the serving engine's
                                decode step (the step thread wedges
                                INSIDE its step lock — the chaos-drill
                                fault for the serving tier,
                                docs/DEBUGGING.md), or the trainer's
                                per-step elastic.note_step hook (hung
                                rank drills — step counter freezes
                                while the heartbeat keeps beating)
  PADDLE_PS_FAULT_SIDE=client|server|both   which transport end injects
                                (default both — set it when client and
                                server share one process env)
  PADDLE_PS_FAULT_SEED=n        deterministic fault schedule

Frame-granular faults (multiplexed channels): target ONE mux frame by
request id — the point is proving a fault is contained to its own call
while concurrent calls on the same socket complete untouched.

  PADDLE_PS_FAULT_FRAME_ACTION=corrupt|drop|delay   what to do to the
                                matched frame: flip a body byte (peer
                                answers that id with a retryable wire
                                error), swallow it (that call times
                                out), or hold it back so later frames
                                overtake it on the wire
  PADDLE_PS_FAULT_FRAME_REQ=id  match: a full 64-bit request id, or
                                "seq:N" to match the low-32-bit
                                sequence number (client token unknown
                                up front), or "any" for the first frame
  PADDLE_PS_FAULT_FRAME_DELAY=sec   hold-back for action=delay
                                (default 0.2)

The frame fault fires ONCE (first matching frame on an injecting side);
tests can re-arm programmatically via ``set_frame_fault``.

Replication-path faults (PS high availability, docs/PS_HA.md): target
the primary->standby WAL replication stream instead of the RPC frames.

  PADDLE_PS_FAULT_REPL_ACTION=drop|corrupt|delay   what to do to ONE
                                matched replication record: skip
                                shipping it (the standby sees a
                                sequence gap and resyncs from a fresh
                                bootstrap), flip its row bytes (the
                                per-record CRC rejects it -> resync),
                                or hold it back FRAME_DELAY seconds
  PADDLE_PS_FAULT_REPL_RECORD=N match: a replication sequence number,
                                or "any" for the first shipped record
                                (default any)
  PADDLE_PS_FAULT_KILL_AT_RECORD=N  standby: os._exit after APPLYING
                                its N-th replicated record (1-based;
                                0 disables) — the deterministic
                                standby-death for semi-sync
                                degradation drills

Like the frame fault, the replication fault fires ONCE; re-arm with
``set_repl_fault``.

Cold-tier faults (tiered embedding store, docs/PS_TIERED.md): target
one demand-paged read from the cold chunk store instead of the wire.

  PADDLE_PS_FAULT_COLD_ACTION=delay|error   what to do to ONE matched
                                cold-tier read: hold it back
                                COLD_DELAY seconds (slow chunk store),
                                or fail it (ColdReadError — the server
                                answers THAT pull with a retryable
                                error and nothing else wedges)
  PADDLE_PS_FAULT_COLD_TABLE=name  match: a table name, or "any"
                                (default any)
  PADDLE_PS_FAULT_COLD_ROW=key  match: a row key the faulting read
                                must include, or "any" (default any)
  PADDLE_PS_FAULT_COLD_DELAY=sec   hold-back for action=delay
                                (default 0.2)

Like the others, the cold fault fires ONCE; re-arm with
``set_cold_fault``.

A PADDLE_PS_FAULT_-prefixed env var that is NOT one of the above is a
typo (a chaos drill that silently injects nothing is worse than one
that fails loudly): `from_env` logs a warning naming it.

Counters (`injector().counters`) are exposed for tests and benchmarks.
"""
from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

__all__ = ["FaultInjector", "injector", "reset_injector",
           "KNOWN_FAULT_KNOBS"]

KILL_EXIT_CODE = 23

# every env knob from_env reads; anything else under the prefix is a
# misspelling the guard below flags
KNOWN_FAULT_KNOBS = frozenset({
    "PADDLE_PS_FAULT_DROP", "PADDLE_PS_FAULT_DELAY",
    "PADDLE_PS_FAULT_TRUNCATE", "PADDLE_PS_FAULT_CORRUPT",
    "PADDLE_PS_FAULT_KILL_AFTER", "PADDLE_PS_FAULT_KILL_POINT",
    "PADDLE_PS_FAULT_KILL_AFTER_BYTES",
    "PADDLE_PS_FAULT_KILL_AT_STEP", "PADDLE_PS_FAULT_STALL",
    "PADDLE_PS_FAULT_STALL_POINT", "PADDLE_PS_FAULT_SIDE",
    "PADDLE_PS_FAULT_SEED", "PADDLE_PS_FAULT_FRAME_ACTION",
    "PADDLE_PS_FAULT_FRAME_REQ", "PADDLE_PS_FAULT_FRAME_DELAY",
    "PADDLE_PS_FAULT_REPL_ACTION", "PADDLE_PS_FAULT_REPL_RECORD",
    "PADDLE_PS_FAULT_KILL_AT_RECORD",
    "PADDLE_PS_FAULT_COLD_ACTION", "PADDLE_PS_FAULT_COLD_TABLE",
    "PADDLE_PS_FAULT_COLD_ROW", "PADDLE_PS_FAULT_COLD_DELAY",
})

logger = logging.getLogger(__name__)


class FaultInjector:
    """One process-wide schedule of transport faults."""

    def __init__(self, drop: float = 0.0, delay: float = 0.0,
                 truncate: float = 0.0, corrupt: float = 0.0,
                 kill_after: int = 0, kill_point: str = "reply",
                 kill_after_bytes: int = 0, kill_at_step: int = -1,
                 stall: float = 0.0,
                 stall_point: str = "dispatch",
                 side: str = "both", seed: int = 0,
                 frame_action: str = "", frame_req: str = "",
                 frame_delay: float = 0.2,
                 repl_action: str = "", repl_record: str = "any",
                 kill_at_record: int = 0,
                 cold_action: str = "", cold_table: str = "any",
                 cold_row: str = "any", cold_delay: float = 0.2):
        self.drop = drop
        self.delay = delay
        self.truncate = truncate
        self.corrupt = corrupt
        self.kill_after = kill_after
        self.kill_point = kill_point
        self.kill_after_bytes = kill_after_bytes
        self.kill_at_step = kill_at_step
        self.stall = stall
        self.stall_point = stall_point
        self.side = side
        self.frame_action = frame_action
        self.frame_req = frame_req
        self.frame_delay = frame_delay
        self._frame_fired = False
        self.repl_action = repl_action
        self.repl_record = repl_record
        self.kill_at_record = kill_at_record
        self._repl_fired = False
        self.cold_action = cold_action
        self.cold_table = cold_table
        self.cold_row = cold_row
        self.cold_delay = cold_delay
        self._cold_fired = False
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()
        self._requests = 0
        self._bytes = 0
        self.counters = {"dropped": 0, "delayed": 0, "truncated": 0,
                         "corrupted": 0, "requests": 0, "bytes": 0,
                         "stalled": 0, "frame_faults": 0,
                         "repl_faults": 0, "cold_faults": 0}

    @classmethod
    def from_env(cls) -> "FaultInjector":
        # typo guard: a misspelled knob (KILL_AFTR, STAL, ...) would
        # otherwise arm NOTHING and the drill would "pass" fault-free
        unknown = sorted(k for k in os.environ
                         if k.startswith("PADDLE_PS_FAULT_")
                         and k not in KNOWN_FAULT_KNOBS)
        if unknown:
            logger.warning(
                "ignoring unknown fault knob(s) %s — known knobs: %s",
                ", ".join(unknown), ", ".join(sorted(KNOWN_FAULT_KNOBS)))
        e = os.environ.get
        return cls(
            drop=float(e("PADDLE_PS_FAULT_DROP", "0") or 0),
            delay=float(e("PADDLE_PS_FAULT_DELAY", "0") or 0),
            truncate=float(e("PADDLE_PS_FAULT_TRUNCATE", "0") or 0),
            corrupt=float(e("PADDLE_PS_FAULT_CORRUPT", "0") or 0),
            kill_after=int(e("PADDLE_PS_FAULT_KILL_AFTER", "0") or 0),
            kill_point=e("PADDLE_PS_FAULT_KILL_POINT", "reply"),
            kill_after_bytes=int(
                e("PADDLE_PS_FAULT_KILL_AFTER_BYTES", "0") or 0),
            kill_at_step=int(
                e("PADDLE_PS_FAULT_KILL_AT_STEP", "-1") or -1),
            stall=float(e("PADDLE_PS_FAULT_STALL", "0") or 0),
            stall_point=e("PADDLE_PS_FAULT_STALL_POINT", "dispatch"),
            side=e("PADDLE_PS_FAULT_SIDE", "both"),
            seed=int(e("PADDLE_PS_FAULT_SEED", "0") or 0),
            frame_action=e("PADDLE_PS_FAULT_FRAME_ACTION", "") or "",
            frame_req=e("PADDLE_PS_FAULT_FRAME_REQ", "any") or "any",
            frame_delay=float(
                e("PADDLE_PS_FAULT_FRAME_DELAY", "0.2") or 0.2),
            repl_action=e("PADDLE_PS_FAULT_REPL_ACTION", "") or "",
            repl_record=e("PADDLE_PS_FAULT_REPL_RECORD", "any")
            or "any",
            kill_at_record=int(
                e("PADDLE_PS_FAULT_KILL_AT_RECORD", "0") or 0),
            cold_action=e("PADDLE_PS_FAULT_COLD_ACTION", "") or "",
            cold_table=e("PADDLE_PS_FAULT_COLD_TABLE", "any") or "any",
            cold_row=e("PADDLE_PS_FAULT_COLD_ROW", "any") or "any",
            cold_delay=float(
                e("PADDLE_PS_FAULT_COLD_DELAY", "0.2") or 0.2))

    @property
    def active(self) -> bool:
        return bool(self.drop or self.delay or self.truncate
                    or self.corrupt or self.kill_after
                    or self.kill_after_bytes or self.kill_at_step >= 0
                    or self.stall or self.frame_action
                    or self.repl_action or self.kill_at_record
                    or self.cold_action)

    def _applies(self, side: str | None) -> bool:
        return self.side == "both" or side is None or side == self.side

    # -- frame-granular faults (multiplexed channels) --------------------
    def set_frame_fault(self, action: str, req: str = "any",
                        delay: float = 0.2, side: str | None = None):
        """(Re)arm a one-shot fault against a single mux frame. `req`
        matches like PADDLE_PS_FAULT_FRAME_REQ: a full id, "seq:N" for
        the low-32-bit sequence, or "any"."""
        with self._lock:
            self.frame_action = action
            self.frame_req = str(req)
            self.frame_delay = delay
            self._frame_fired = False
            if side is not None:
                self.side = side

    def _frame_matches(self, req_id: int) -> bool:
        spec = self.frame_req
        if spec in ("", "any"):
            return True
        if spec.startswith("seq:"):
            return (req_id & 0xFFFFFFFF) == int(spec[4:])
        return req_id == int(spec)

    def frame_fault(self, req_id: int,
                    side: str | None) -> tuple[str, float] | None:
        """One-shot fault check for a single outgoing mux frame.
        Returns None (send normally) or (action, delay_seconds) with
        action in {"corrupt", "drop", "delay"} — the fault is consumed
        by the first matching frame on an injecting side."""
        if not self.frame_action or not self._applies(side):
            return None
        with self._lock:
            if self._frame_fired or not self._frame_matches(req_id):
                return None
            self._frame_fired = True
            self.counters["frame_faults"] += 1
            return self.frame_action, self.frame_delay

    # -- replication-stream faults (PS HA, docs/PS_HA.md) ----------------
    def set_repl_fault(self, action: str, record: str = "any",
                       delay: float = 0.2):
        """(Re)arm a one-shot fault against a single primary->standby
        replication record. `record` is a replication sequence number
        or "any" for the next shipped record."""
        with self._lock:
            self.repl_action = action
            self.repl_record = str(record)
            self.frame_delay = delay
            self._repl_fired = False

    def repl_fault(self, seq: int) -> tuple[str, float] | None:
        """One-shot fault check for one outgoing replication record.
        Returns None (ship normally) or (action, delay_seconds) with
        action in {"drop", "corrupt", "delay"} — consumed by the first
        matching record."""
        if not self.repl_action:
            return None
        with self._lock:
            if self._repl_fired:
                return None
            spec = self.repl_record
            if spec not in ("", "any") and int(seq) != int(spec):
                return None
            self._repl_fired = True
            self.counters["repl_faults"] += 1
            return self.repl_action, self.frame_delay

    # -- cold-tier faults (tiered store, docs/PS_TIERED.md) --------------
    def set_cold_fault(self, action: str, table: str = "any",
                       row: str = "any", delay: float = 0.2):
        """(Re)arm a one-shot fault against a single cold-tier read.
        `table` matches a table name or "any"; `row` matches a key the
        faulting read must include, or "any"."""
        with self._lock:
            self.cold_action = action
            self.cold_table = str(table)
            self.cold_row = str(row)
            self.cold_delay = delay
            self._cold_fired = False

    def cold_fault(self, table: str,
                   keys) -> tuple[str, float] | None:
        """One-shot fault check for one cold-tier read. Returns None
        (read normally) or (action, delay_seconds) with action in
        {"delay", "error"} — consumed by the first matching read."""
        if not self.cold_action:
            return None
        with self._lock:
            if self._cold_fired:
                return None
            if self.cold_table not in ("", "any") \
                    and str(table) != self.cold_table:
                return None
            if self.cold_row not in ("", "any"):
                want = int(self.cold_row)
                if not any(int(k) == want for k in keys):
                    return None
            self._cold_fired = True
            self.counters["cold_faults"] += 1
            return self.cold_action, self.cold_delay

    def maybe_kill_at_record(self, n: int):
        """Standby kill switch: dies (os._exit, a SIGKILL stand-in)
        once it has APPLIED its ``kill_at_record``-th replicated record
        — the record is in its tables/WAL but possibly un-acked, the
        exact window the semi-sync degradation drill needs."""
        if self.kill_at_record and int(n) >= self.kill_at_record:
            os._exit(KILL_EXIT_CODE)

    # -- frame mangling (called from rpc.send_frame) --------------------
    def mangle(self, frame: bytes, body_off: int, side: str | None,
               req_id: int | None = None) -> tuple[bytes, str]:
        """Returns (frame', action) where action is one of
        "send" | "drop" | "truncate" | "skip" ("skip": the frame is
        consumed without a send AND without killing the connection —
        only the frame-granular path produces it)."""
        if req_id is not None and self.frame_action:
            act = self.frame_fault(req_id, side)
            if act is not None:
                kind, _delay = act
                if kind == "drop":
                    return frame, "skip"
                if kind == "delay":
                    time.sleep(_delay)
                elif kind == "corrupt" and len(frame) > body_off:
                    buf = bytearray(frame)
                    buf[body_off] ^= 0xFF
                    frame = bytes(buf)
        if not self._applies(side):
            return frame, "send"
        with self._lock:
            if self.delay:
                self.counters["delayed"] += 1
                delay = self.delay
            else:
                delay = 0.0
            if self.drop and self._rng.rand() < self.drop:
                self.counters["dropped"] += 1
                return frame, "drop"
            if self.truncate and self._rng.rand() < self.truncate:
                self.counters["truncated"] += 1
                return frame, "truncate"
            if self.corrupt and len(frame) > body_off \
                    and self._rng.rand() < self.corrupt:
                self.counters["corrupted"] += 1
                buf = bytearray(frame)
                pos = body_off + int(
                    self._rng.randint(0, len(frame) - body_off))
                buf[pos] ^= 0xFF
                frame = bytes(buf)
        if delay:
            time.sleep(delay)
        return frame, "send"

    # -- server kill switch ---------------------------------------------
    def count_request(self):
        """Server side, one call per received request; returns True when
        the kill threshold was just crossed."""
        with self._lock:
            self._requests += 1
            self.counters["requests"] = self._requests
            return bool(self.kill_after
                        and self._requests >= self.kill_after)

    def maybe_kill(self, point: str, armed: bool):
        if armed and self.kill_point == point:
            os._exit(KILL_EXIT_CODE)

    # -- hang injection (watchdog tests) ---------------------------------
    def maybe_stall(self, point: str, side: str | None = None):
        """Wedge the calling thread for `stall` seconds — a tier that is
        alive but making no progress, which is the failure mode the
        stall watchdog (observability/watchdog.py) exists to detect."""
        if self.stall and self.stall_point == point \
                and self._applies(side):
            with self._lock:
                self.counters["stalled"] += 1
            time.sleep(self.stall)

    # -- trainer kill switch (gang-restart chaos drills) ------------------
    def maybe_kill_at_step(self, step: int):
        """Dies (os._exit, no cleanup — a SIGKILL stand-in) at the
        START of training step ``kill_at_step``: state reflects the
        previous step, the coordinated checkpoint of it may be
        mid-flight — exactly the crash the gang-restart resume drill
        must survive. elastic.note_step calls this every step."""
        if self.kill_at_step >= 0 and int(step) >= self.kill_at_step:
            os._exit(KILL_EXIT_CODE)

    # -- writer kill switch (checkpoint crash tests) ---------------------
    def maybe_kill_bytes(self, n: int):
        """One call per payload write of n bytes; dies mid-save once the
        byte threshold is crossed (BEFORE the write's rename publishes
        it, so the crash leaves a torn, uncommitted tail)."""
        with self._lock:
            self._bytes += n
            self.counters["bytes"] = self._bytes
            armed = bool(self.kill_after_bytes
                         and self._bytes >= self.kill_after_bytes)
        if armed:
            os._exit(KILL_EXIT_CODE)


_injector: FaultInjector | None = None


def injector() -> FaultInjector:
    """Process-wide injector, configured from env on first use."""
    global _injector
    if _injector is None:
        _injector = FaultInjector.from_env()
    return _injector


def reset_injector(inj: FaultInjector | None = None):
    """Tests: swap in a fresh injector (None = re-read the env)."""
    global _injector
    _injector = inj
