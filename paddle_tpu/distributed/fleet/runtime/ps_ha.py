"""Parameter-server high availability: live WAL replication, epoch
fencing, and shard promotion (docs/PS_HA.md).

Roles: every PSServer is a *primary* unless constructed with a primary
endpoint (``PADDLE_PS_HA_PRIMARY`` / ``primary=``), which makes it a
hot *standby*. A primary wraps its row-level WAL journal in
:class:`ReplicatedJournal`, so every committed record (touched rows +
request id + reply blob) is also published — in journal append order —
to the :class:`ReplicationHub`, whose ``repl_watch`` subscribers
(standbys) receive it over the multiplexed wire as server-push frames.
The standby applies each record through the same ensure+assign path
WAL replay uses, commits the request id into its own dedup cache, and
appends to its OWN journal; its tables, RNG streams, and exactly-once
state track the primary row-for-row.

Failover is epoch-fenced: promotion bumps the shard epoch, clients
carry the epoch they last saw (``_epoch`` in the request skeleton),
and a zombie ex-primary that sees a NEWER epoch fences itself and
rejects writes with ``stale_epoch`` — a late write can never fork the
shard. Planned handoff (``ha_handoff``) runs drain -> catch-up ->
epoch flip under the primary's apply lock, so in-flight pushes finish
first and queued ones redirect to the new primary with the SAME
request ids (zero failed pushes, dedup preserved).

Ack modes: replication is async by default. ``PADDLE_PS_HA_SEMISYNC=K``
holds each push's reply until K standbys acked the journaled record
(``wait_semisync``, called from the RPC layer's before_reply hook —
outside the commit scope, so waiting never serializes other pushes).
When standbys die or lag past ``PADDLE_PS_HA_SEMISYNC_TIMEOUT``, the
ack degrades to async — counted and flight-evented — instead of
stalling trainers.
"""
from __future__ import annotations

import queue
import threading
import time
import zlib

import numpy as np

from ....observability import flight as _flight, registry as _obs
from ....checkpoint.wal import RowJournal
from .fault_injection import injector

__all__ = ["ReplicationHub", "ReplicatedJournal", "StandbyReplicator",
           "promote_best", "record_crc", "set_role_gauges",
           "note_promotion", "note_handoff", "note_fenced_write"]

_ROLE = _obs.gauge(
    "paddle_tpu_ps_ha_role",
    "PS shard role: 1 primary, 0 standby", ["endpoint"])
_EPOCH = _obs.gauge(
    "paddle_tpu_ps_ha_epoch",
    "fencing epoch of this PS shard", ["endpoint"])
_STANDBYS = _obs.gauge(
    "paddle_tpu_ps_ha_standbys_connected",
    "replication subscribers currently attached to this primary",
    ["endpoint"])
_LAG_ROWS = _obs.gauge(
    "paddle_tpu_ps_ha_replication_lag_rows",
    "journal records shipped but not yet acked by this standby",
    ["endpoint", "peer"])
_LAG_BYTES = _obs.gauge(
    "paddle_tpu_ps_ha_replication_lag_bytes",
    "journal bytes shipped but not yet acked by this standby",
    ["endpoint", "peer"])
_LAG_SECONDS = _obs.gauge(
    "paddle_tpu_ps_ha_replication_lag_seconds",
    "age of the newest record this standby has acked",
    ["endpoint", "peer"])
_SHIPPED = _obs.counter(
    "paddle_tpu_ps_ha_records_shipped_total",
    "replication records published to standby subscribers",
    ["endpoint"])
_SEMISYNC = _obs.counter(
    "paddle_tpu_ps_ha_semisync_total",
    "semi-sync ack waits by outcome (acked|degraded)", ["outcome"])
_FENCED = _obs.counter(
    "paddle_tpu_ps_ha_fenced_writes_total",
    "mutating ops rejected by epoch fencing (stale_epoch)")
_PROMOTIONS = _obs.counter(
    "paddle_tpu_ps_ha_promotions_total",
    "standby -> primary promotions on this process")
_HANDOFFS = _obs.counter(
    "paddle_tpu_ps_ha_handoffs_total",
    "planned primary handoffs completed by this process")
_RESYNCS = _obs.counter(
    "paddle_tpu_ps_ha_resyncs_total",
    "standby full resyncs (gap, CRC mismatch, or reconnect)")


def set_role_gauges(endpoint: str, role: str, epoch: int):
    """Keep the role/epoch gauges current across promotion/demotion
    (single registration site for every paddle_tpu_ps_ha_* metric is
    this module)."""
    _ROLE.labels(endpoint=endpoint).set(1 if role == "primary" else 0)
    _EPOCH.labels(endpoint=endpoint).set(int(epoch))


def note_promotion(endpoint: str, epoch: int, reason: str = ""):
    _PROMOTIONS.inc()
    _flight.record("ps", "ha_promote", endpoint=endpoint,
                   epoch=int(epoch), reason=reason)


def note_handoff(endpoint: str, target: str, epoch: int):
    _HANDOFFS.inc()
    _flight.record("ps", "ha_handoff", endpoint=endpoint,
                   target=target, epoch=int(epoch))


def note_fenced_write(endpoint: str, op: str, req_epoch: int,
                      epoch: int):
    _FENCED.inc()
    _flight.record("ps", "ha_fenced_write", endpoint=endpoint, op=op,
                   req_epoch=int(req_epoch), epoch=int(epoch))


def record_crc(values) -> int:
    """CRC32 over a rows-record's value bytes: the standby verifies it
    per record, so a corrupt replication frame is detected and answered
    with a resync instead of silently forking the shard."""
    return zlib.crc32(np.ascontiguousarray(
        np.asarray(values, np.float32)).tobytes()) & 0xFFFFFFFF


class _ReplSub:
    """One standby's replication feed: a bounded record queue plus ack
    bookkeeping. Overflow marks the subscriber broken — it tears down
    and resyncs from a fresh bootstrap rather than silently skipping
    records (a gap on this stream is shard divergence)."""

    def __init__(self, sid: int, name: str, maxsize: int):
        self.sid = sid
        self.name = name
        self.q: queue.Queue = queue.Queue(maxsize)
        self.broken = False
        self.acked_seq = 0
        self.acked_bytes = 0
        self.acked_t = 0.0


class ReplicationHub:
    """Primary-side fan-out of committed WAL records to standbys.

    ``order_lock`` is held around journal-append + publish (see
    ReplicatedJournal), so the publish sequence numbers records in
    exactly journal append order — the order standby replay must
    reproduce. Subscription and the bootstrap state export happen under
    the server's apply lock, so a subscriber can never miss a record
    committed after its bootstrap (duplicates across the boundary are
    possible for appends outside the apply lock — sync-barrier rows —
    and are benign: apply is idempotent and the standby skips
    already-applied sequence numbers).
    """

    def __init__(self, endpoint: str, semisync: int | None = None,
                 semisync_timeout: float | None = None,
                 queue_max: int | None = None):
        import os
        env = os.environ.get
        self.endpoint = endpoint
        self.semisync = semisync if semisync is not None else int(
            env("PADDLE_PS_HA_SEMISYNC", "0") or 0)
        self.semisync_timeout = semisync_timeout \
            if semisync_timeout is not None else float(
                env("PADDLE_PS_HA_SEMISYNC_TIMEOUT", "1.0") or 1.0)
        self.queue_max = queue_max if queue_max is not None else int(
            env("PADDLE_PS_HA_QUEUE", "4096") or 0)
        self.order_lock = threading.Lock()
        self._cond = threading.Condition()
        self._subs: dict[int, _ReplSub] = {}
        self._next_sid = 0
        self.seq = 0            # newest published record (monotone)
        self.bytes = 0          # cumulative journal bytes published
        self.last_t = 0.0       # stamp of the newest published record
        self.degraded = 0       # semi-sync waits that fell back to async
        # req_id -> (seq, bytes) of its journal record, consumed by
        # wait_semisync; bounded so a crashed waiter cannot leak it
        self._req_seq: dict[int, tuple[int, int]] = {}

    # -- subscriber lifecycle -------------------------------------------
    def subscribe(self, name: str) -> _ReplSub:
        with self._cond:
            sid = self._next_sid
            self._next_sid += 1
            sub = _ReplSub(sid, name, self.queue_max)
            # a fresh subscriber is caught up to the bootstrap instant
            sub.acked_seq = self.seq
            sub.acked_bytes = self.bytes
            sub.acked_t = self.last_t
            self._subs[sid] = sub
            self._set_gauges_locked()
        return sub

    def unsubscribe(self, sub: _ReplSub):
        with self._cond:
            self._subs.pop(sub.sid, None)
            self._set_gauges_locked()
            self._cond.notify_all()
        for m in (_LAG_ROWS, _LAG_BYTES, _LAG_SECONDS):
            m.remove_matching(endpoint=self.endpoint, peer=sub.name)

    def find(self, name: str) -> _ReplSub | None:
        with self._cond:
            for sub in self._subs.values():
                if sub.name == name and not sub.broken:
                    return sub
        return None

    def status(self) -> list[dict]:
        with self._cond:
            return [{"peer": s.name, "acked_seq": s.acked_seq,
                     "lag_rows": self.seq - s.acked_seq,
                     "broken": s.broken}
                    for s in self._subs.values()]

    def _set_gauges_locked(self):
        _STANDBYS.labels(endpoint=self.endpoint).set(
            sum(1 for s in self._subs.values() if not s.broken))

    def _set_lag_locked(self, sub: _ReplSub):
        _LAG_ROWS.labels(endpoint=self.endpoint, peer=sub.name).set(
            max(0, self.seq - sub.acked_seq))
        _LAG_BYTES.labels(endpoint=self.endpoint, peer=sub.name).set(
            max(0, self.bytes - sub.acked_bytes))
        lag_s = 0.0
        if self.seq > sub.acked_seq and sub.acked_t:
            lag_s = max(0.0, time.time() - sub.acked_t)
        _LAG_SECONDS.labels(endpoint=self.endpoint,
                            peer=sub.name).set(lag_s)

    # -- publish (under order_lock, from ReplicatedJournal) -------------
    def publish(self, rec: dict, req_id: int = 0, nbytes: int = 0):
        with self._cond:
            self.seq += 1
            self.bytes += int(nbytes)
            self.last_t = time.time()
            rec = dict(rec, seq=self.seq, t=self.last_t)
            if self.semisync > 0 and req_id:
                self._req_seq[req_id] = (self.seq, self.bytes)
                while len(self._req_seq) > 8192:
                    self._req_seq.pop(next(iter(self._req_seq)))
            subs = list(self._subs.values())
            for sub in subs:
                if sub.broken:
                    continue
                try:
                    sub.q.put_nowait(rec)
                except queue.Full:
                    # slower than the push rate for a full queue's
                    # worth: kill this feed, the standby resyncs
                    sub.broken = True
            self._set_gauges_locked()
            for sub in subs:
                self._set_lag_locked(sub)
        if subs:
            _SHIPPED.labels(endpoint=self.endpoint).inc(len(
                [s for s in subs if not s.broken]))
        return rec["seq"]

    # -- acks (repl_ack verb) -------------------------------------------
    def ack(self, sid: int, seq: int, nbytes: int = 0, t: float = 0.0):
        with self._cond:
            sub = self._subs.get(int(sid))
            if sub is None:
                return False
            sub.acked_seq = max(sub.acked_seq, int(seq))
            sub.acked_bytes = max(sub.acked_bytes, int(nbytes))
            if t:
                sub.acked_t = float(t)
            self._set_lag_locked(sub)
            self._cond.notify_all()
        return True

    def wait_semisync(self, req_id: int):
        """Hold one push's reply until K live standbys acked its
        record. Degrades (counted + flight event) instead of blocking
        past the timeout or when fewer than K standbys are alive."""
        k = self.semisync
        if k <= 0:
            return
        degraded_seq = None
        with self._cond:
            entry = self._req_seq.pop(req_id, None)
            if entry is None:
                return
            seq, _b = entry
            deadline = time.monotonic() + self.semisync_timeout
            while True:
                live = [s for s in self._subs.values() if not s.broken]
                if sum(1 for s in live if s.acked_seq >= seq) >= k:
                    _SEMISYNC.labels(outcome="acked").inc()
                    return
                left = deadline - time.monotonic()
                if len(live) < k or left <= 0:
                    break
                self._cond.wait(timeout=min(left, 0.05))
            self.degraded += 1
            degraded_seq = seq
        _SEMISYNC.labels(outcome="degraded").inc()
        _flight.record("ps", "ha_semisync_degraded",
                       endpoint=self.endpoint, seq=degraded_seq,
                       want=k)

    def wait_caught_up(self, sub: _ReplSub, seq: int,
                       timeout: float) -> bool:
        """Handoff catch-up: block until `sub` acked through `seq`."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if sub.acked_seq >= seq:
                    return True
                if sub.broken or sub.sid not in self._subs:
                    return False
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=min(left, 0.25))


class ReplicatedJournal(RowJournal):
    """RowJournal that publishes every append to a ReplicationHub.

    ``order_lock`` spans append + publish: two concurrent appends
    cannot ship in an order different from their on-disk order, which
    is the order standby replay reproduces."""

    def __init__(self, path: str, hub: ReplicationHub, **kw):
        super().__init__(path, **kw)
        self.hub = hub

    @staticmethod
    def _extra_arr(extra: bytes) -> np.ndarray:
        return np.frombuffer(extra, np.uint8) if extra \
            else np.empty(0, np.uint8)

    def append_rows(self, table, idx, values, *, dim=None,
                    init_std: float = 0.01, seed: int = 0,
                    req_id: int = 0, extra: bytes = b"") -> int:
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        values = np.ascontiguousarray(
            np.asarray(values, np.float32)).reshape(len(idx), -1)
        with self.hub.order_lock:
            n = super().append_rows(table, idx, values, dim=dim,
                                    init_std=init_std, seed=seed,
                                    req_id=req_id, extra=extra)
            self.hub.publish(
                {"kind": "rows", "table": str(table),
                 "dim": int(dim if dim is not None
                            else values.shape[1]),
                 "init_std": float(init_std), "seed": int(seed),
                 "idx": idx, "values": values, "req_id": int(req_id),
                 "extra": self._extra_arr(extra),
                 "crc": record_crc(values)},
                req_id=int(req_id), nbytes=n)
        return n

    def append_mark(self, req_id: int, extra: bytes = b"") -> int:
        with self.hub.order_lock:
            n = super().append_mark(req_id, extra)
            self.hub.publish(
                {"kind": "mark", "req_id": int(req_id),
                 "extra": self._extra_arr(extra)},
                req_id=int(req_id), nbytes=n)
        return n

    def publish_rotate(self, wal_seq: int):
        """Rotation/compaction marker: tells standbys the primary
        folded its journal into a fresh base, so they compact their own
        journal too (re-anchoring their local replay chain)."""
        with self.hub.order_lock:
            self.hub.publish({"kind": "rotate",
                              "wal_seq": int(wal_seq)})


class StandbyReplicator:
    """Standby-side replication client: subscribes to the primary's
    ``repl_watch`` stream, imports the bootstrap state, then applies
    each record in sequence through the server's WAL-replay path. A
    gap, CRC mismatch, or transport error tears the stream down and
    resyncs from a fresh bootstrap (counted). A coalescing ack thread
    reports the applied high-water mark back to the primary (semi-sync
    acks + lag gauges)."""

    def __init__(self, server, primary: str):
        self.server = server
        self.primary = primary
        self.stop = threading.Event()
        self.applied_seq = 0
        self.records_applied = 0
        self.resyncs = 0
        self.synced = threading.Event()  # bootstrap imported at least once
        self.last_error: str | None = None
        self._ack_cond = threading.Condition()
        self._ack_t = 0.0
        self._client = None  # live RpcClient, closed() kills it
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ps-ha-repl-{server.endpoint}")

    def start(self) -> "StandbyReplicator":
        self._thread.start()
        return self

    def close(self):
        self.stop.set()
        cl = self._client
        if cl is not None:
            # sever the live stream so promotion/shutdown never waits
            # out a recv timeout on a quiet primary
            try:
                cl.close()
            except Exception:
                pass
        with self._ack_cond:
            self._ack_cond.notify_all()

    # -- main loop -------------------------------------------------------
    def _run(self):
        from .rpc import RpcClient
        while not self.stop.is_set() \
                and self.server.ha_role == "standby":
            cl = RpcClient(self.primary, timeout=15.0, deadline=20.0,
                           max_retries=1)
            self._client = cl
            ack_stop = threading.Event()
            gen = None
            try:
                gen = cl.call_stream(
                    {"op": "repl_watch", "name": self.server.endpoint},
                    timeout=30.0, stream_timeout=12.0)
                first = next(gen)
                if not isinstance(first, dict) \
                        or "bootstrap" not in first:
                    raise RuntimeError(
                        f"bad repl_watch bootstrap: {type(first)}")
                sid = int(first["sub"])
                self.server._ha_import_bootstrap(
                    first["bootstrap"], int(first["seq"]),
                    int(first["epoch"]))
                self.applied_seq = int(first["seq"])
                self.synced.set()
                ack_th = threading.Thread(
                    target=self._ack_loop, args=(cl, sid, ack_stop),
                    daemon=True,
                    name=f"ps-ha-ack-{self.server.endpoint}")
                ack_th.start()
                self._consume(gen)
            except Exception as e:
                if self.stop.is_set() \
                        or self.server.ha_role != "standby":
                    return
                self.last_error = f"{type(e).__name__}: {e}"
                self.resyncs += 1
                _RESYNCS.inc()
                _flight.record("ps", "ha_resync",
                               endpoint=self.server.endpoint,
                               primary=self.primary,
                               error=self.last_error)
            finally:
                ack_stop.set()
                with self._ack_cond:
                    self._ack_cond.notify_all()
                if gen is not None:
                    try:
                        gen.close()
                    except Exception:
                        pass
                self._client = None
                cl.close()
            self.stop.wait(0.2)

    def _consume(self, gen):
        inj = injector()
        for rec in gen:
            if self.stop.is_set() \
                    or self.server.ha_role != "standby":
                return
            if not isinstance(rec, dict):
                continue
            kind = rec.get("kind")
            if kind == "keepalive":
                continue
            seq = int(rec.get("seq", 0))
            if seq <= self.applied_seq:
                continue  # duplicate across the bootstrap boundary
            if seq != self.applied_seq + 1:
                raise RuntimeError(
                    f"replication gap {self.applied_seq} -> {seq}")
            if kind == "rows" and "crc" in rec \
                    and record_crc(rec["values"]) != int(rec["crc"]):
                raise RuntimeError(
                    f"replication record {seq} failed CRC")
            if kind == "rotate":
                self.server._ha_note_rotate()
            else:
                self.server._ha_apply_record(rec)
            self.applied_seq = seq
            self.records_applied += 1
            if inj.active:
                inj.maybe_kill_at_record(self.records_applied)
            with self._ack_cond:
                self._ack_t = float(rec.get("t", 0.0))
                self._ack_cond.notify_all()
        # generator returned a final reply: the primary ended the
        # stream (demotion/shutdown) — treat as disconnect
        raise ConnectionError("replication stream ended")

    def _ack_loop(self, cl, sid: int, ack_stop: threading.Event):
        sent = -1
        while not ack_stop.is_set():
            with self._ack_cond:
                self._ack_cond.wait_for(
                    lambda: ack_stop.is_set()
                    or self.applied_seq != sent, timeout=1.0)
                seq, t = self.applied_seq, self._ack_t
            if ack_stop.is_set():
                return
            if seq == sent:
                continue
            try:
                cl.call({"op": "repl_ack", "sub": sid, "seq": seq,
                         "bytes": self.server._ha_replicated_bytes,
                         "t": t},
                        timeout=5.0, deadline=5.0, max_retries=0)
                sent = seq
            except Exception:
                if ack_stop.wait(0.2):
                    return


def promote_best(candidates: list[str], epoch: int,
                 timeout: float = 10.0) -> str | None:
    """Failover: probe `candidates` (standby endpoints), pick the
    most-caught-up live one, and promote it with `epoch`. Returns the
    promoted endpoint, or None when no candidate answered. If a
    candidate already claims primary at `epoch` or newer (a racing
    promoter won), it is returned as-is."""
    from .rpc import RpcClient
    best_ep, best_seq = None, -1
    for ep in candidates:
        cl = RpcClient(ep, timeout=2.0, deadline=min(timeout, 4.0),
                       max_retries=0)
        try:
            st = cl.call({"op": "ha_status"}, timeout=2.0)
        except Exception:
            continue
        finally:
            cl.close()
        if not isinstance(st, dict):
            continue
        if st.get("role") == "primary" \
                and int(st.get("epoch", 0)) >= int(epoch):
            return ep
        seq = int(st.get("applied_seq", 0))
        if seq > best_seq:
            best_ep, best_seq = ep, seq
    if best_ep is None:
        return None
    cl = RpcClient(best_ep, timeout=5.0, deadline=timeout,
                   max_retries=1)
    try:
        cl.call({"op": "ha_promote", "epoch": int(epoch)},
                timeout=5.0)
    except Exception:
        return None
    finally:
        cl.close()
    return best_ep
