"""Tiered embedding parameter store: hot/warm/cold row hierarchy with
demand paging from the content-addressed chunk store (docs/PS_TIERED.md).

Production recommenders hold 10^9+ embedding rows — far beyond one
host's RAM (reference: the Paddle fleet/heter-PS hierarchy). This
module gives :class:`~.parameter_server_runtime.PSServer` a per-table
opt-in replacement for ``LargeScaleKV`` that keeps only the frequently
accessed rows resident:

  hot   worker-side rows in the PR-11 ``boxps_cache`` hot-row cache
        (client tier — unchanged by this module; server pushes
        invalidations exactly as before)
  warm  rows in host RAM on the shard, inside the byte budget
        (``PADDLE_PS_TIER_WARM_BYTES``)
  cold  rows demand-paged from a local ``CheckpointStore`` chunk store
        via its ``read_rows`` row-range reads

Admission/eviction is frequency-based: every access bumps a per-slot
counter (exponentially decayed each demotion pass), and a background
demoter evicts the coldest rows once warm residency crosses the
budget, down to a low watermark. Rows with an up-to-date cold copy
(faulted in, never pushed since) are *reverted* for free; dirty rows
are flushed as an immutable row segment whose chunks go through
``ChunkStore.put`` — written entirely OFF the table lock and the
server's apply lock, then committed row-by-row so rows touched during
the write simply stay warm.

Bit-exactness contract (the WAL/HA parity property): faulting a row in
or demoting it never changes its value and never touches the table's
init RNG stream; only creating a genuinely new row draws from the RNG,
through the identical batched-draw path ``LargeScaleKV._ensure`` uses.
``apply_rows`` (WAL replay / HA replication apply) admits cold keys
directly with the journaled post-values — the original apply saw an
existing row, so replay must not draw either. ``export_state``
materializes cold rows back into the flat keys/rows arrays, so
snapshots, HA bootstraps, and parity checks see exactly the state an
all-warm table would hold.

Failure containment: a failing cold read (chunk missing/corrupt, or an
injected ``PADDLE_PS_FAULT_COLD_ACTION=error``) raises
:class:`ColdReadError` — the server turns it into an error reply for
THAT pull only; nothing is admitted, evicted, or wedged, and the
retried pull re-faults cleanly. A failing segment *write* leaves the
victims warm (budget temporarily exceeded) and is retried next pass.
"""
from __future__ import annotations

import os
import threading
import time
import weakref

import numpy as np

from ....observability import registry as _obs
from .fault_injection import injector
from .parameter_server_runtime import LargeScaleKV

__all__ = ["TieredTable", "ColdReadError", "gc_cold_store"]

# -- tier telemetry (single registration site; the invariants rule's
# REQUIRED set and the collector/top tier pane read these exact names)
_HITS = _obs.counter(
    "paddle_tpu_ps_tier_hits_total",
    "rows served by tier: warm = resident RAM, cold = demand-paged "
    "from the chunk store (hot-tier hits live on the worker cache)",
    ["tier"])
_MISSES = _obs.counter(
    "paddle_tpu_ps_tier_misses_total",
    "rows resident in NO tier at access time (lazy-init creations)")
_FAULTS = _obs.counter(
    "paddle_tpu_ps_tier_faults_total",
    "cold rows faulted into the warm tier")
_DEMOTIONS = _obs.counter(
    "paddle_tpu_ps_tier_demotions_total",
    "rows demoted warm->cold: clean = cold copy still valid (free), "
    "flush = dirty rows written as a fresh segment", ["kind"])
_COLD_ERRORS = _obs.counter(
    "paddle_tpu_ps_tier_cold_read_errors_total",
    "failed cold-tier reads (chunk missing/corrupt or injected) — "
    "each fails only its own pull")
_PULL_SECONDS = _obs.histogram(
    "paddle_tpu_ps_tier_pull_seconds",
    "table-level pull latency by serving tier (cold = the pull "
    "demand-paged at least one row)", ["tier"],
    buckets=(1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
             5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 1.0))
_RES_ROWS = _obs.gauge(
    "paddle_tpu_ps_tier_resident_rows",
    "rows resident per tier across this process's tiered tables",
    ["tier"])
_RES_BYTES = _obs.gauge(
    "paddle_tpu_ps_tier_resident_bytes",
    "row payload bytes resident per tier across this process's "
    "tiered tables", ["tier"])

# live tables for the exposition-time resident gauges (evaluated
# outside the series lock; len() reads need no table lock)
_TABLES: "weakref.WeakSet[TieredTable]" = weakref.WeakSet()


def _sum_tables(fn) -> float:
    return float(sum(fn(t) for t in list(_TABLES)))


_RES_ROWS.labels(tier="warm").set_function(
    lambda: _sum_tables(lambda t: len(t._index)))
_RES_ROWS.labels(tier="cold").set_function(
    lambda: _sum_tables(lambda t: len(t._cold)))
_RES_BYTES.labels(tier="warm").set_function(
    lambda: _sum_tables(lambda t: len(t._index) * t.row_bytes))
_RES_BYTES.labels(tier="cold").set_function(
    lambda: _sum_tables(lambda t: len(t._cold) * t.row_bytes))


class ColdReadError(RuntimeError):
    """A cold-tier read failed (chunk missing/corrupt or injected).
    Contained to the one pull that needed the row — the server answers
    that request with an error frame and stays healthy."""


def _demote_loop(ref, stop: threading.Event, interval: float):
    """Background demoter body: module-level + weakref so an abandoned
    table is collectable (the thread exits when the ref dies)."""
    while not stop.wait(interval):
        t = ref()
        if t is None:
            return
        try:
            t.demote()
        except Exception:
            pass  # never kill the demoter; next pass retries
        del t


class TieredTable(LargeScaleKV):
    """``LargeScaleKV`` with a byte-budgeted warm tier and a cold tier
    demand-paged from a chunk store. Numpy-only: tier bookkeeping
    lives in per-slot arrays parallel to the row arena, so the native
    core is bypassed even when built.

    ``store`` is a ``paddle_tpu.checkpoint.store.CheckpointStore``
    (its ``chunks`` + ``read_rows`` are the only parts used; no
    manifests are ever committed). Segments are hand-built manifest
    ``arrays`` entries kept in memory — crash recovery of the cold
    tier is NOT this table's job: the WAL/snapshot tier already
    journals every row, and ``export_state`` rematerializes cold rows,
    so a restart rebuilds from base+journal and re-demotes.
    """

    def __init__(self, dim: int, init_std: float = 0.01, seed: int = 0,
                 *, store, name: str = "", warm_bytes: int = 0,
                 low_frac: float = 0.8, demote_interval: float = 0.0):
        super().__init__(dim, init_std=init_std, seed=seed)
        self._native = None  # tier bookkeeping needs the numpy arena
        self._store = store
        self.name = name
        self.row_bytes = int(dim) * 4  # float32 rows
        self.warm_bytes = int(warm_bytes)
        self.low_frac = float(low_frac)
        # per-slot bookkeeping, parallel to the _data arena
        self._slot_key = np.empty(0, np.int64)    # -1 = free slot
        self._freq = np.zeros(0, np.float64)      # decayed access count
        self._stamp = np.zeros(0, np.int64)       # last-touch tick
        self._clean_seg = np.empty(0, np.int64)   # valid cold copy seg
        self._clean_row = np.zeros(0, np.int64)   # ... and its row
        self._top = 0                             # arena high-water
        self._free: list[int] = []
        self._tick = 0
        # cold tier: key -> (seg, row); seg -> {"ent", "live", "total"}
        self._cold: dict[int, tuple[int, int]] = {}
        self._segs: dict[int, dict] = {}
        self._next_seg = 0
        self._export_pins = 0  # in-flight exports pin chunks vs GC
        # per-table stats (bench/tests; the registry carries the
        # process-wide aggregates)
        self.warm_hits = 0
        self.cold_faults = 0
        self.creates = 0
        self.demoted_clean = 0
        self.demoted_flush = 0
        self.cold_read_errors = 0
        _TABLES.add(self)
        self._demote_stop = threading.Event()
        if demote_interval > 0:
            threading.Thread(
                target=_demote_loop,
                args=(weakref.ref(self), self._demote_stop,
                      float(demote_interval)),
                daemon=True, name=f"ps-tier-demote-{name}").start()
        weakref.finalize(self, self._demote_stop.set)

    def close(self):
        """Stop the background demoter (PSServer.server_close)."""
        self._demote_stop.set()

    # -- slot allocation (free-list: eviction punches holes the base
    # class's dense start=len(index) allocator cannot reuse) ------------
    def _alloc_slot(self) -> int:
        if self._free:
            return self._free.pop()
        if self._top >= len(self._data):
            cap = max(self._top + 1, 2 * len(self._data) + 64)
            self._data = self._grown(self._data, cap)
            self._slot_key = self._grown(self._slot_key, cap, -1)
            self._freq = self._grown(self._freq, cap, 0)
            self._stamp = self._grown(self._stamp, cap, 0)
            self._clean_seg = self._grown(self._clean_seg, cap, -1)
            self._clean_row = self._grown(self._clean_row, cap, 0)
        s = self._top
        self._top += 1
        return s

    @staticmethod
    def _grown(a: np.ndarray, cap: int, fill=None) -> np.ndarray:
        shape = (cap,) + a.shape[1:]
        out = np.empty(shape, a.dtype) if fill is None \
            else np.full(shape, fill, a.dtype)
        out[:len(a)] = a
        return out

    def _ensure(self, keys: np.ndarray) -> np.ndarray:
        """Base-class contract (create truly-missing rows), free-list
        slots. The RNG draw is bit-identical to the base: ONE batched
        normal over the deduped missing keys in first-occurrence order
        — callers must have faulted/admitted every cold key first, or
        a cold row would be shadowed by a fresh draw."""
        idx = self._index
        missing = list(dict.fromkeys(
            k for k in keys.tolist() if k not in idx))
        if missing:
            fresh = self._rng.normal(
                0, self.init_std,
                (len(missing), self.dim)).astype(np.float32)
            for i, k in enumerate(missing):
                s = self._alloc_slot()
                self._data[s] = fresh[i]
                idx[k] = s
                self._slot_key[s] = k
                self._freq[s] = 0.0
                self._stamp[s] = self._tick
                self._clean_seg[s] = -1
            self.creates += len(missing)
            _MISSES.inc(len(missing))
        return np.fromiter((idx[k] for k in keys.tolist()), np.int64,
                           len(keys))

    def _seg_unref(self, seg: int):
        e = self._segs.get(seg)
        if e is not None:
            e["live"] -= 1
            if e["live"] <= 0:
                del self._segs[seg]  # chunks die at the next GC pass

    def _dirty_slots(self, slots: np.ndarray):
        """A write landed on these slots: any clean cold copy is stale
        now, so the WAL journal hook's rows_for sees post-values and a
        later demotion must flush, not revert."""
        for s in set(slots.tolist()):
            seg = int(self._clean_seg[s])
            if seg >= 0:
                self._clean_seg[s] = -1
                self._seg_unref(seg)

    def _cold_among(self, ks: list[int]) -> list[int]:
        return [k for k in dict.fromkeys(ks)
                if k not in self._index and k in self._cold]

    # -- cold-tier IO (always OUTSIDE self._lock) ------------------------
    def _read_refs(self, refs: dict[int, tuple[int, int]],
                   ents: dict[int, dict]) -> dict[int, np.ndarray]:
        """Read the rows behind ``refs`` (key -> (seg, row)) from the
        store, coalescing adjacent rows per segment into range reads.
        Raises ColdReadError on any failed chunk read."""
        by_seg: dict[int, list[tuple[int, int]]] = {}
        for k, (seg, row) in refs.items():
            by_seg.setdefault(seg, []).append((row, k))
        got: dict[int, np.ndarray] = {}
        for seg, pairs in by_seg.items():
            pairs.sort()
            i = 0
            while i < len(pairs):
                j = i
                while j + 1 < len(pairs) \
                        and pairs[j + 1][0] == pairs[j][0] + 1:
                    j += 1
                lo, hi = pairs[i][0], pairs[j][0] + 1
                try:
                    block = self._store.read_rows(ents[seg], lo, hi)
                except Exception as e:
                    self.cold_read_errors += 1
                    _COLD_ERRORS.inc()
                    raise ColdReadError(
                        f"cold_read_failed table={self.name!r} "
                        f"seg={seg} rows=[{lo},{hi}): {e}") from e
                for p in range(i, j + 1):
                    got[pairs[p][1]] = block[pairs[p][0] - lo]
                i = j + 1
        return got

    def _fault_in(self, cold_keys: list[int]) -> int:
        """Demand-page ``cold_keys`` into the warm tier. The chunk
        reads run outside the table lock; admission re-checks each ref
        so a raced eviction/re-admission is skipped, never clobbered.
        Missing keys raise KeyError (rows_for contract)."""
        inj = injector()
        if inj.active:
            act = inj.cold_fault(self.name, cold_keys)
            if act is not None:
                action, delay = act
                if action == "error":
                    self.cold_read_errors += 1
                    _COLD_ERRORS.inc()
                    raise ColdReadError(
                        f"cold_read_failed (injected) "
                        f"table={self.name!r}")
                if action == "delay":
                    time.sleep(delay)
        with self._lock:
            refs = {}
            for k in cold_keys:
                r = self._cold.get(k)
                if r is not None:
                    refs[k] = r
                elif k not in self._index:
                    raise KeyError(k)
            ents = {seg: self._segs[seg]["ent"]
                    for seg in {r[0] for r in refs.values()}}
        got = self._read_refs(refs, ents)
        with self._lock:
            self._tick += 1
            n = 0
            for k, v in got.items():
                if self._cold.get(k) != refs[k]:
                    continue  # raced with another fault/GC decision
                del self._cold[k]
                s = self._alloc_slot()
                self._index[k] = s
                self._slot_key[s] = k
                self._data[s] = v
                self._freq[s] = 1.0
                self._stamp[s] = self._tick
                seg, row = refs[k]
                # cold ref becomes a clean ref: seg live is unchanged
                self._clean_seg[s] = seg
                self._clean_row[s] = row
                n += 1
            self.cold_faults += n
        _FAULTS.inc(n)
        _HITS.labels(tier="cold").inc(n)
        return n

    # -- table surface ---------------------------------------------------
    def pull_ex(self, keys) -> tuple[np.ndarray, int]:
        """Pull plus the number of rows demand-paged (the server wraps
        a faulting reply so PSClient can count cold faults)."""
        t0 = time.perf_counter()
        ks = np.asarray(keys, np.int64).ravel()
        faults = 0
        while True:
            with self._lock:
                self._tick += 1
                cold = self._cold_among(ks.tolist())
                if not cold:
                    nwarm = sum(1 for k in dict.fromkeys(ks.tolist())
                                if k in self._index)
                    slots = self._ensure(ks)
                    self._freq[slots] += 1.0
                    self._stamp[slots] = self._tick
                    out = self._data[slots].copy()
                    break
            faults += self._fault_in(cold)
        self.warm_hits += nwarm - faults if faults else nwarm
        _HITS.labels(tier="warm").inc(max(nwarm - faults, 0))
        _PULL_SECONDS.labels(
            tier="cold" if faults else "warm").observe(
            time.perf_counter() - t0)
        return out, faults

    def pull(self, keys) -> np.ndarray:
        return self.pull_ex(keys)[0]

    def push(self, keys, grads, lr: float = 1.0):
        """Fault-then-apply: cold rows are paged in first, so the
        apply (and the WAL journal hook's rows_for read) always sees
        warm rows — journaling stays touched-rows-only and standbys
        track tier transitions row-for-row."""
        ks = np.asarray(keys, np.int64).ravel()
        while True:
            with self._lock:
                self._tick += 1
                cold = self._cold_among(ks.tolist())
                if not cold:
                    slots = self._ensure(ks)
                    np.add.at(self._data, slots,
                              (-lr * np.asarray(grads))
                              .astype(np.float32))
                    self._dirty_slots(slots)
                    self._freq[slots] += 1.0
                    self._stamp[slots] = self._tick
                    return
            self._fault_in(cold)

    def rows_for(self, keys) -> np.ndarray:
        ks = np.asarray(keys, np.int64).ravel()
        while True:
            with self._lock:
                cold = [k for k in dict.fromkeys(ks.tolist())
                        if k not in self._index]
                if not cold:
                    slots = np.fromiter(
                        (self._index[int(k)] for k in ks.tolist()),
                        np.int64, len(ks))
                    return self._data[slots].copy()
            self._fault_in(cold)  # KeyError for truly-missing keys

    def missing_keys(self, keys) -> np.ndarray:
        """Keys resident in NO tier (exactly what a pull would lazily
        create — cold rows are NOT missing, faulting consumes no RNG)."""
        with self._lock:
            idx, cold = self._index, self._cold
            return np.fromiter(
                dict.fromkeys(
                    k for k in np.asarray(keys, np.int64)
                    .ravel().tolist()
                    if k not in idx and k not in cold),
                np.int64)

    def apply_rows(self, keys, rows):
        """WAL replay / HA replication apply. Cold keys are admitted
        DIRECTLY with the journaled post-values — on the primary the
        row existed (no RNG draw), so replay reads no store and draws
        nothing; only truly-new keys go through _ensure's batched
        draw. Bit-exact against the original apply order."""
        with self._lock:
            self._tick += 1
            ks = np.asarray(keys, np.int64).ravel()
            vals = np.asarray(rows, np.float32).reshape(len(ks),
                                                        self.dim)
            for k in dict.fromkeys(ks.tolist()):
                ref = self._cold.get(k)
                if ref is None or k in self._index:
                    continue
                del self._cold[k]
                s = self._alloc_slot()
                self._index[k] = s
                self._slot_key[s] = k
                self._freq[s] = 1.0
                self._stamp[s] = self._tick
                self._clean_seg[s] = -1
                self._seg_unref(ref[0])  # journaled value supersedes
            slots = self._ensure(ks)
            self._data[slots] = vals
            self._dirty_slots(slots)
            self._stamp[slots] = self._tick

    def size(self) -> int:
        with self._lock:
            return len(self._index) + len(self._cold)

    def warm_resident_bytes(self) -> int:
        return len(self._index) * self.row_bytes

    def stats(self) -> dict:
        with self._lock:
            return {"warm_rows": len(self._index),
                    "cold_rows": len(self._cold),
                    "warm_bytes": len(self._index) * self.row_bytes,
                    "segments": len(self._segs),
                    "warm_hits": self.warm_hits,
                    "cold_faults": self.cold_faults,
                    "creates": self.creates,
                    "demoted_clean": self.demoted_clean,
                    "demoted_flush": self.demoted_flush,
                    "cold_read_errors": self.cold_read_errors}

    # -- demotion (watermark-driven, off the apply lock) -----------------
    def demote(self) -> int:
        """One demotion pass: when warm residency exceeds the budget,
        evict the lowest-frequency rows (oldest-stamp tie-break) down
        to the low watermark. Clean rows revert to their existing cold
        copy under the lock; dirty rows are flushed as a fresh segment
        whose chunk writes run with NO lock held, then committed
        row-by-row — a row touched during the write stays warm.
        Rows touched at the current tick are never victims (livelock
        guard: a faulting pull always completes before its row can be
        re-evicted). Returns rows demoted."""
        with self._lock:
            resident = len(self._index) * self.row_bytes
            if self.warm_bytes <= 0 or resident <= self.warm_bytes:
                return 0
            # open a new tick: rows stamped before it are fair game,
            # rows a concurrently-faulting pull admits land at the new
            # tick and survive until that pull has served them
            self._tick += 1
            cut = self._tick
            target = int(self.warm_bytes * self.low_frac)
            need = -(-(resident - target) // self.row_bytes)
            act = np.flatnonzero(self._slot_key[:self._top] >= 0)
            act = act[self._stamp[act] < cut]
            if not len(act):
                return 0
            order = np.lexsort((self._stamp[act], self._freq[act]))
            victims = act[order][:need]
            self._freq[:self._top] *= 0.5  # age the access counts
            clean = victims[self._clean_seg[victims] >= 0]
            for s in clean.tolist():
                k = int(self._slot_key[s])
                del self._index[k]
                self._cold[k] = (int(self._clean_seg[s]),
                                 int(self._clean_row[s]))
                self._slot_key[s] = -1
                self._clean_seg[s] = -1
                self._free.append(s)
            nclean = len(clean)
            self.demoted_clean += nclean
            dirty_slots = victims[self._clean_seg[victims] < 0]
            dirty = [(int(self._slot_key[s]), int(s),
                      int(self._stamp[s]))
                     for s in dirty_slots.tolist()]
            vals = self._data[dirty_slots].copy() if len(dirty_slots) \
                else None
        if nclean:
            _DEMOTIONS.labels(kind="clean").inc(nclean)
        if not dirty:
            return nclean
        # flush the dirty victims as one immutable segment — chunk
        # writes on the demoter thread only, no lock held
        blob = vals.tobytes()
        ent = {"dtype": np.dtype(np.float32).str,
               "shape": [len(dirty), self.dim],
               "nbytes": len(blob), "chunks": []}
        cb = int(getattr(self._store, "chunk_bytes", 1 << 20))
        try:
            for off in range(0, len(blob), cb):
                piece = blob[off:off + cb]
                ent["chunks"].append(
                    {"h": self._store.chunks.put(piece), "o": off,
                     "n": len(piece)})
        except Exception:
            # store write failed: victims stay warm (budget exceeded
            # until the next pass succeeds) — never wedge the shard
            self.cold_read_errors += 1
            _COLD_ERRORS.inc()
            return nclean
        with self._lock:
            seg = self._next_seg
            self._next_seg += 1
            live = 0
            for row, (k, s, st0) in enumerate(dirty):
                if self._index.get(k) != s \
                        or int(self._slot_key[s]) != k \
                        or int(self._stamp[s]) != st0 \
                        or self._clean_seg[s] >= 0:
                    continue  # touched during the write: stays warm
                del self._index[k]
                self._cold[k] = (seg, row)
                self._slot_key[s] = -1
                self._free.append(s)
                live += 1
            if live:
                self._segs[seg] = {"ent": ent, "live": live,
                                   "total": len(dirty)}
            self.demoted_flush += live
        if live:
            _DEMOTIONS.labels(kind="flush").inc(live)
        return nclean + live

    def drain(self, passes: int = 64) -> int:
        """Synchronously demote until under budget (tests/bench)."""
        n = 0
        for _ in range(passes):
            d = self.demote()
            n += d
            if not d:
                break
        return n

    # -- snapshot/HA export-import ---------------------------------------
    def export_state(self) -> dict:
        """Materialize the WHOLE table — warm rows plus cold rows read
        back from the store — into the flat keys/rows/rng dict every
        consumer of LargeScaleKV state understands. Point-in-time:
        warm rows are copied under the lock, cold segment bytes are
        immutable, and in-flight exports pin chunks against GC."""
        with self._lock:
            keys_w = np.fromiter(self._index, np.int64,
                                 len(self._index))
            slots = np.fromiter(self._index.values(), np.int64,
                                len(self._index))
            rows_w = self._data[slots].copy()
            rng = self._rng.get_state()
            cold = dict(self._cold)
            ents = {seg: self._segs[seg]["ent"]
                    for seg in {r[0] for r in cold.values()}}
            self._export_pins += 1
        try:
            got = self._read_refs(cold, ents) if cold else {}
        finally:
            with self._lock:
                self._export_pins -= 1
        if got:
            keys_c = np.fromiter(got, np.int64, len(got))
            rows_c = np.stack([got[int(k)] for k in keys_c])
            keys = np.concatenate([keys_w, keys_c])
            rows = np.concatenate([rows_w, rows_c]) if len(keys_w) \
                else rows_c
        else:
            keys, rows = keys_w, rows_w
        return {"dim": self.dim, "init_std": self.init_std,
                "seed": self.seed, "keys": keys, "rows": rows,
                "rng": {"alg": rng[0],
                        "key": np.asarray(rng[1], np.uint32),
                        "pos": int(rng[2]),
                        "has_gauss": int(rng[3]),
                        "cached": float(rng[4])}}

    def import_state(self, st: dict):
        """Restore from a flat export: everything lands WARM (the
        demoter re-demotes under the budget asynchronously); prior
        segments are dropped — their chunks age out via gc_cold_store."""
        with self._lock:
            self._tick += 1
            self.dim = int(st["dim"])
            self.init_std = float(st.get("init_std", self.init_std))
            self.seed = int(st.get("seed", self.seed))
            self.row_bytes = self.dim * 4
            keys = np.asarray(st["keys"], np.int64)
            rows = np.asarray(st["rows"], np.float32)
            n = len(keys)
            self._data = np.ascontiguousarray(
                rows.reshape(n, self.dim))
            self._index = {int(k): i for i, k in enumerate(keys)}
            self._slot_key = keys.copy()
            self._freq = np.zeros(n, np.float64)
            self._stamp = np.full(n, self._tick, np.int64)
            self._clean_seg = np.full(n, -1, np.int64)
            self._clean_row = np.zeros(n, np.int64)
            self._top = n
            self._free = []
            self._cold = {}
            self._segs = {}
            rng = st.get("rng")
            if rng is not None:
                self._rng.set_state((
                    str(rng["alg"]),
                    np.asarray(rng["key"], np.uint32),
                    int(rng["pos"]), int(rng["has_gauss"]),
                    float(rng["cached"])))


def gc_cold_store(store, tables, min_age: float = 60.0) -> int:
    """Drop cold-store chunks no live segment references. Age-guarded
    (mtime older than ``min_age`` seconds) so a segment being written
    concurrently — its chunks exist on disk before its table registers
    the ent — is never collected; in-flight exports skip the pass
    entirely. Runs after full base snapshots; never raises."""
    try:
        live: set[str] = set()
        for t in tables:
            if not isinstance(t, TieredTable) or t._store is not store:
                continue
            with t._lock:
                if t._export_pins:
                    return 0
                for e in t._segs.values():
                    for c in e["ent"]["chunks"]:
                        live.add(c["h"])
        n = 0
        now = time.time()
        for d in store.chunks.all_digests():
            if d in live:
                continue
            p = store.chunks._path(d)
            try:
                if now - os.path.getmtime(p) < min_age:
                    continue
                os.unlink(p)
                n += 1
            except OSError:
                continue
        return n
    except Exception:
        return 0
