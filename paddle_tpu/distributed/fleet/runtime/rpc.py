"""Fault-tolerant RPC layer for the PS/heter tier.

Replaces the seed's length-prefixed-pickle transport with a data-only
wire format plus client retry and server dedup. Reference analog: the
brpc channel options (timeout_ms / max_retry / backoff) and the
gRPC/BRPC request framing under operators/distributed/, re-expressed as
a dependency-free protocol:

  frame   := header || body
  header  := magic u16 | ver u8 | flags u8 | req_id u64 | crc u32
             | body_len u64                      (24 bytes, little-endian)
  body    := skel_len u32 | skeleton(JSON) | segment*
  segment := dtype u8 | ndim u8 | dims i64*ndim | raw row-major bytes

The skeleton is plain JSON (dict/list/str/number/bool/null) where every
ndarray was replaced by {"__nd__": k}; segments carry the arrays in
order. Decoding therefore never evaluates attacker-controlled code —
`json.loads` plus `np.frombuffer` against a dtype whitelist — unlike the
pickle path this replaces (ADVICE: RCE if bound beyond localhost).

Integrity/auth:
  * crc32 over the body rejects corrupted frames (fault tolerance, not
    security — CRC is not a MAC).
  * optional shared-secret handshake: when PADDLE_PS_SECRET is set on
    the server, every connection must answer an HMAC-SHA256 challenge
    before the first request. See docs/PS_WIRE_PROTOCOL.md for the
    remaining trusted-network assumptions.

Client semantics (`RpcClient.call`):
  * per-request deadline + per-attempt timeout,
  * exponential backoff with jitter, bounded retries/reconnects,
  * a stable request id across retries; the server dedups mutating ops
    by id, so a retried gradient push is applied exactly once. Callers
    that own failover across SERVERS (the serving router) can pin the
    id themselves via ``req_id=`` so a replay on whichever replica —
    original or survivor — carries the same identity.

Server-push streaming: a dispatch function may return a GENERATOR.
`serve_connection` then sends every yielded object as an ``F_STREAM``
frame (same request id) and the generator's return value as the normal
final reply — which is what the dedup cache memoises, so a retried
streamed op is answered with the final frame only. Clients consume the
pushed frames via ``call(..., on_stream=fn)``; the per-attempt socket
timeout bounds the INTER-FRAME gap, which is how the serving router
detects a replica wedged mid-generation (docs/SERVING.md).
"""
from __future__ import annotations

import contextlib
import hmac
import hashlib
import json
import os
import random
import socket
import struct
import threading
import time
import types
import zlib

import numpy as np

from ....observability import (flight as _flight, registry as _obs,
                               tracing as _tracing)
from .fault_injection import injector

__all__ = [
    "WireError", "PSAuthError", "PSRemoteError", "PSDeadlineError",
    "encode_body", "decode_body", "send_frame", "recv_frame",
    "TransportStats", "RpcClient", "DedupCache", "RpcServerState",
    "serve_connection", "PROTOCOL_VERSION", "TRACE_KEY", "F_STREAM",
]

PROTOCOL_VERSION = 1
_MAGIC = 0x7053                      # "Sp" — PS rpc

# transport telemetry on the process-wide registry. The skeleton may
# carry a `_trace_id` field (injected by RpcClient.call, stripped by
# serve_connection before dispatch) so one request is followable
# worker -> PS server and frontend -> engine across processes.
TRACE_KEY = "_trace_id"
_CLIENT_EVENTS = _obs.counter(
    "paddle_tpu_rpc_client_events_total",
    "client transport events (requests/retries/timeouts/...)",
    ["event"])
_CLIENT_BYTES = _obs.counter(
    "paddle_tpu_rpc_client_bytes_total",
    "client wire bytes by direction", ["direction"])
_CLIENT_LATENCY = _obs.histogram(
    "paddle_tpu_rpc_client_latency_seconds",
    "successful call() round-trip latency incl. retries", ["op"])
_SERVER_REQS = _obs.counter(
    "paddle_tpu_rpc_server_requests_total",
    "requests received by serve_connection", ["op"])
_SERVER_ERRORS = _obs.counter(
    "paddle_tpu_rpc_server_errors_total",
    "dispatch failures answered with an error frame", ["op"])
_SERVER_DEDUP_HITS = _obs.counter(
    "paddle_tpu_rpc_server_dedup_hits_total",
    "mutating requests answered from the dedup cache (client retries)",
    ["op"])
_HDR = struct.Struct("<HBBQIQ")      # magic, ver, flags, req_id, crc, len
HEADER_SIZE = _HDR.size
F_ERROR = 1
F_HANDSHAKE = 2
F_STREAM = 4                         # server-push frame; more follow
_MAX_BODY = 1 << 31                  # sanity bound on a length field

_ND_KEY = "__nd__"

# dtype whitelist: receiving anything else is a wire error, never an
# object/pickle dtype
_DTYPES = [np.dtype(s) for s in (
    "float32", "float64", "float16", "int64", "int32", "int16", "int8",
    "uint64", "uint32", "uint16", "uint8", "bool")]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}


class WireError(ConnectionError):
    """Malformed/corrupt frame — the connection is no longer trusted."""


class PSAuthError(RuntimeError):
    """Handshake failure. Not retryable."""


class PSRemoteError(RuntimeError):
    """The server dispatched the request and replied with an error."""


class PSDeadlineError(ConnectionError):
    """Retries/deadline exhausted without a successful round-trip."""


# ---------------------------------------------------------------------------
# body codec: JSON skeleton + dtype/shape-tagged ndarray segments
# ---------------------------------------------------------------------------

def encode_body(obj) -> bytes:
    arrays: list[np.ndarray] = []

    def strip(o):
        if isinstance(o, np.ndarray):
            arrays.append(o)
            return {_ND_KEY: len(arrays) - 1}
        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, dict):
            return {str(k): strip(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [strip(v) for v in o]
        return o

    skel = json.dumps(strip(obj)).encode("utf-8")
    parts = [struct.pack("<I", len(skel)), skel]
    for a in arrays:
        a = np.ascontiguousarray(a)
        code = _DTYPE_CODE.get(a.dtype)
        if code is None:
            raise TypeError(
                f"dtype {a.dtype} is not wire-safe (whitelist: "
                f"{[str(d) for d in _DTYPES]})")
        parts.append(struct.pack("<BB", code, a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def decode_body(buf: bytes):
    if len(buf) < 4:
        raise WireError("body too short")
    (skel_len,) = struct.unpack_from("<I", buf, 0)
    if 4 + skel_len > len(buf):
        raise WireError("skeleton length exceeds body")
    try:
        skel = json.loads(buf[4:4 + skel_len].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"bad skeleton: {e}") from None
    arrays: list[np.ndarray] = []
    off = 4 + skel_len
    while off < len(buf):
        if off + 2 > len(buf):
            raise WireError("truncated segment header")
        code, ndim = struct.unpack_from("<BB", buf, off)
        off += 2
        if code >= len(_DTYPES) or ndim > 16:
            raise WireError(f"bad segment tag ({code}, {ndim})")
        if off + 8 * ndim > len(buf):
            raise WireError("truncated segment dims")
        dims = struct.unpack_from(f"<{ndim}q", buf, off)
        off += 8 * ndim
        if any(d < 0 for d in dims):
            raise WireError(f"negative dim {dims}")
        dt = _DTYPES[code]
        # python-int product: immune to the int64 overflow a hostile
        # dims vector could use to slip past the bounds check
        count = 1
        for d in dims:
            count *= d
        nbytes = count * dt.itemsize if ndim else dt.itemsize
        if nbytes > len(buf) - off:
            raise WireError("segment data exceeds body")
        try:
            arr = np.frombuffer(buf, dt, count=nbytes // dt.itemsize,
                                offset=off).reshape(dims)
        except ValueError as e:
            raise WireError(f"bad segment geometry: {e}") from None
        arrays.append(arr)
        off += nbytes

    def build(o):
        if isinstance(o, dict):
            if set(o) == {_ND_KEY} and isinstance(o[_ND_KEY], int):
                k = o[_ND_KEY]
                if not 0 <= k < len(arrays):
                    raise WireError(f"dangling array ref {k}")
                return arrays[k]
            return {k: build(v) for k, v in o.items()}
        if isinstance(o, list):
            return [build(v) for v in o]
        return o

    return build(skel)


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, obj, req_id: int = 0,
               flags: int = 0, side: str | None = None) -> int:
    body = encode_body(obj)
    frame = _HDR.pack(_MAGIC, PROTOCOL_VERSION, flags, req_id,
                      zlib.crc32(body), len(body)) + body
    inj = injector()
    if inj.active:
        frame, action = inj.mangle(frame, HEADER_SIZE, side)
        if action == "drop":
            sock.close()
            raise ConnectionError("fault-injected frame drop")
        if action == "truncate":
            try:
                sock.sendall(frame[:max(len(frame) // 2, 1)])
            finally:
                sock.close()
            raise ConnectionError("fault-injected frame truncation")
    sock.sendall(frame)
    return len(frame)


def _recvn(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket, side: str | None = None):
    """Returns (obj, req_id, flags, frame_bytes). Raises WireError on a
    frame that fails validation — the stream is desynced, the caller
    must close the connection."""
    hdr = _recvn(sock, HEADER_SIZE)
    magic, ver, flags, req_id, crc, body_len = _HDR.unpack(hdr)
    if magic != _MAGIC:
        raise WireError(f"bad magic 0x{magic:04x}")
    if ver != PROTOCOL_VERSION:
        raise WireError(f"protocol version {ver} != {PROTOCOL_VERSION}")
    if body_len > _MAX_BODY:
        raise WireError(f"body length {body_len} exceeds bound")
    body = _recvn(sock, body_len)
    if zlib.crc32(body) != crc:
        raise WireError("crc mismatch (corrupt frame)")
    return decode_body(body), req_id, flags, HEADER_SIZE + body_len


# ---------------------------------------------------------------------------
# handshake: protocol version + optional HMAC shared secret
# ---------------------------------------------------------------------------

def _mac(secret: str, nonce: str) -> str:
    return hmac.new(secret.encode(), nonce.encode(),
                    hashlib.sha256).hexdigest()


def server_handshake(sock: socket.socket, secret: str | None):
    nonce = os.urandom(16).hex() if secret else None
    send_frame(sock, {"ver": PROTOCOL_VERSION, "nonce": nonce},
               flags=F_HANDSHAKE)
    reply, _rid, flags, _n = recv_frame(sock)
    if not flags & F_HANDSHAKE:
        raise WireError("expected handshake reply")
    if secret is not None:
        mac = reply.get("mac") if isinstance(reply, dict) else None
        if not (isinstance(mac, str)
                and hmac.compare_digest(mac, _mac(secret, nonce))):
            send_frame(sock, {"error": "authentication failed",
                              "kind": "auth"}, flags=F_ERROR)
            raise PSAuthError("client failed the PADDLE_PS_SECRET "
                              "challenge")
    send_frame(sock, {"ok": True}, flags=F_HANDSHAKE)


def client_handshake(sock: socket.socket, secret: str | None):
    hello, _rid, flags, _n = recv_frame(sock)
    if not flags & F_HANDSHAKE or not isinstance(hello, dict):
        raise WireError("expected handshake hello")
    if hello.get("ver") != PROTOCOL_VERSION:
        raise PSAuthError(
            f"server protocol version {hello.get('ver')} != "
            f"{PROTOCOL_VERSION}")
    nonce = hello.get("nonce")
    if nonce is not None and secret is None:
        raise PSAuthError(
            "server requires a shared secret — set PADDLE_PS_SECRET")
    mac = _mac(secret, nonce) if nonce is not None else None
    send_frame(sock, {"mac": mac}, flags=F_HANDSHAKE)
    ok, _rid, flags, _n = recv_frame(sock)
    if flags & F_ERROR:
        raise PSAuthError(str(ok.get("error", "handshake rejected"))
                          if isinstance(ok, dict) else "rejected")
    if not flags & F_HANDSHAKE:
        raise WireError("expected handshake ack")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class TransportStats:
    """Thread-safe transport counters, shared across a client's
    per-endpoint connections (tests/benchmarks read these)."""

    _FIELDS = ("requests", "retries", "reconnects", "timeouts",
               "corrupt_frames", "remote_errors", "deadline_exceeded")

    def __init__(self):
        self._lock = threading.Lock()
        self.bytes_out = 0
        self.bytes_in = 0
        for f in self._FIELDS:
            setattr(self, f, 0)

    def add(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        # mirror into the process-wide registry (PSClient.stats keeps
        # its exact per-client surface; /metrics shows the aggregate)
        _CLIENT_EVENTS.labels(event=field).inc(n)

    def add_bytes(self, n_out: int, n_in: int):
        with self._lock:
            self.bytes_out += n_out
            self.bytes_in += n_in
        _CLIENT_BYTES.labels(direction="out").inc(n_out)
        _CLIENT_BYTES.labels(direction="in").inc(n_in)

    def as_dict(self) -> dict:
        with self._lock:
            d = {f: getattr(self, f) for f in self._FIELDS}
            d["bytes_out"] = self.bytes_out
            d["bytes_in"] = self.bytes_in
            return d


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


class RpcClient:
    """One endpoint's fault-tolerant channel: lazy connect + handshake,
    per-request deadline, exponential backoff with jitter, bounded
    retries, and stable request ids for server-side dedup."""

    def __init__(self, endpoint: str, stats: TransportStats | None = None,
                 secret: str | None = None,
                 timeout: float | None = None,
                 deadline: float | None = None,
                 max_retries: int | None = None,
                 backoff: float | None = None,
                 backoff_max: float = 2.0):
        self.endpoint = endpoint
        self.stats = stats if stats is not None else TransportStats()
        self.secret = secret if secret is not None \
            else os.environ.get("PADDLE_PS_SECRET")
        self.timeout = timeout if timeout is not None \
            else _env_float("PADDLE_PS_TIMEOUT", 60.0)
        self.deadline = deadline if deadline is not None \
            else _env_float("PADDLE_PS_DEADLINE", 600.0)
        self.max_retries = max_retries if max_retries is not None \
            else int(_env_float("PADDLE_PS_RETRIES", 64))
        self.backoff = backoff if backoff is not None \
            else _env_float("PADDLE_PS_BACKOFF", 0.05)
        self.backoff_max = backoff_max
        self._sock: socket.socket | None = None
        self._ever_connected = False
        self._lock = threading.Lock()
        # request ids stay unique across client restarts of THIS process
        # but not across client processes — a 32-bit random token
        # namespaces the 32-bit sequence
        self._token = int.from_bytes(os.urandom(4), "little")
        self._seq = 0
        self._streaming = False      # call_stream exclusivity guard

    def _next_id(self) -> int:
        self._seq = (self._seq + 1) & 0xFFFFFFFF
        return (self._token << 32) | self._seq

    def _connect(self, attempt_timeout: float):
        host, port = self.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)),
                                     timeout=attempt_timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            client_handshake(s, self.secret)
        except BaseException:
            s.close()
            raise
        if self._ever_connected:
            self.stats.add("reconnects")
        self._ever_connected = True
        self._sock = s

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, req, timeout: float | None = None,
             deadline: float | None = None, on_stream=None,
             req_id: int | None = None):
        """One request/reply round-trip; retried with the same request
        id until success, the deadline, or the retry bound. The span's
        trace id rides in the skeleton (TRACE_KEY) so the server side
        of this call joins the same trace.

        ``on_stream`` receives every F_STREAM frame the server pushes
        before the final reply (streamed ops); the per-attempt timeout
        then bounds the INTER-FRAME gap, not the whole call. Pushed
        frames are advisory progress — on a retry the final reply is
        the authoritative result (a dedup hit replays no stream
        frames). ``req_id`` pins the wire request id (serving-router
        failover: the SAME id must ride the replay on a surviving
        replica so a later retry against the original still dedups)."""
        op = req.get("op") if isinstance(req, dict) else None
        with _tracing.span("rpc.client", op=op or "?",
                           endpoint=self.endpoint) as sp:
            if isinstance(req, dict) and TRACE_KEY not in req:
                req = {**req, TRACE_KEY: sp.trace_id}
            t_call = time.monotonic()
            try:
                rep = self._call_locked(req, timeout, deadline,
                                        on_stream=on_stream,
                                        req_id=req_id)
            except Exception as e:
                _flight.record("rpc", "client_error",
                               trace_id=sp.trace_id, op=op or "?",
                               endpoint=self.endpoint,
                               error=f"{type(e).__name__}: {e}")
                raise
            dt = time.monotonic() - t_call
            _CLIENT_LATENCY.labels(op=op or "?").observe(dt)
            _flight.record("rpc", "client_call", trace_id=sp.trace_id,
                           op=op or "?", endpoint=self.endpoint,
                           seconds=round(dt, 6))
            return rep

    def _call_locked(self, req, timeout, deadline, on_stream=None,
                     req_id=None):
        per_attempt = timeout if timeout is not None else self.timeout
        deadline_ts = time.monotonic() + (
            deadline if deadline is not None else self.deadline)
        attempt = 0
        last: Exception | None = None
        with self._lock:
            self.stats.add("requests")
            while True:
                remaining = deadline_ts - time.monotonic()
                if remaining <= 0 or attempt > self.max_retries:
                    self.stats.add("deadline_exceeded")
                    raise PSDeadlineError(
                        f"PS request to {self.endpoint} failed after "
                        f"{attempt} attempt(s): {last}") from last
                try:
                    if self._sock is None:
                        self._connect(min(5.0, max(remaining, 0.1)))
                    if req_id is None:
                        req_id = self._next_id()
                    s = self._sock
                    s.settimeout(min(per_attempt, max(remaining, 0.1)))
                    n_out = send_frame(s, req, req_id=req_id,
                                       side="client")
                    while True:
                        rep, rid, flags, n_in = recv_frame(
                            s, side="client")
                        self.stats.add_bytes(n_out, n_in)
                        n_out = 0
                        if rid != req_id:
                            raise WireError(
                                f"reply id {rid:#x} != "
                                f"request {req_id:#x}")
                        if not flags & F_STREAM:
                            break
                        # pushed progress frame: hand to the consumer,
                        # keep the attempt open. The socket timeout set
                        # above bounds the gap to the NEXT frame — a
                        # wedged streamer surfaces as socket.timeout.
                        if on_stream is not None:
                            on_stream(rep)
                    if flags & F_ERROR:
                        self.stats.add("remote_errors")
                        msg = rep.get("error", "remote error") \
                            if isinstance(rep, dict) else str(rep)
                        if isinstance(rep, dict) \
                                and rep.get("kind") == "auth":
                            raise PSAuthError(msg)
                        raise PSRemoteError(msg)
                    return rep
                except (PSAuthError, PSRemoteError):
                    raise
                except WireError as e:
                    last = e
                    self.stats.add("corrupt_frames")
                except socket.timeout as e:
                    last = e
                    self.stats.add("timeouts")
                except (ConnectionError, OSError) as e:
                    last = e
                self._drop()
                self.stats.add("retries")
                attempt += 1
                pause = min(self.backoff * (2 ** (attempt - 1)),
                            self.backoff_max)
                time.sleep(pause * (0.5 + random.random()))

    def call_stream(self, req, req_id: int | None = None,
                    timeout: float | None = None,
                    stream_timeout: float | None = None):
        """Single-attempt streaming call: a GENERATOR yielding each
        F_STREAM frame the server pushes, returning the final reply as
        its StopIteration value. No internal retry — the caller owns
        failover (the serving router replays on a different replica
        with the SAME ``req_id`` so dedup still holds; docs/SERVING.md).

        ``timeout`` bounds the wait for the FIRST frame (queueing +
        prefill happen before any token); ``stream_timeout`` bounds
        every later INTER-FRAME gap — a replica wedged mid-generation
        surfaces as socket.timeout here, which is the router's
        mid-stream stall signal. Transport errors propagate raw; the
        connection is dropped on any abnormal exit (including an
        abandoned generator) because a half-consumed stream desyncs it.

        The caller must own this client exclusively for the stream's
        lifetime (the router's per-replica pool guarantees it); unlike
        ``call()`` no channel lock is held across the yields, so
        concurrent use is a caller bug — guarded by a busy flag."""
        if self._streaming:
            raise RuntimeError("call_stream: client already streaming")
        op = req.get("op") if isinstance(req, dict) else None
        first_t = timeout if timeout is not None else self.timeout
        gap_t = stream_timeout if stream_timeout is not None else first_t
        self._streaming = True
        ok = False
        try:
            with _tracing.span("rpc.client_stream", op=op or "?",
                               endpoint=self.endpoint) as sp:
                if isinstance(req, dict) and TRACE_KEY not in req:
                    req = {**req, TRACE_KEY: sp.trace_id}
                self.stats.add("requests")
                if self._sock is None:
                    self._connect(min(5.0, first_t))
                rid = req_id if req_id is not None else self._next_id()
                s = self._sock
                s.settimeout(first_t)
                n_out = send_frame(s, req, req_id=rid, side="client")
                first = True
                while True:
                    try:
                        rep, r_rid, flags, n_in = recv_frame(
                            s, side="client")
                    except socket.timeout:
                        self.stats.add("timeouts")
                        raise
                    self.stats.add_bytes(n_out, n_in)
                    n_out = 0
                    if r_rid != rid:
                        raise WireError(f"reply id {r_rid:#x} != "
                                        f"request {rid:#x}")
                    if flags & F_ERROR:
                        self.stats.add("remote_errors")
                        msg = rep.get("error", "remote error") \
                            if isinstance(rep, dict) else str(rep)
                        raise PSRemoteError(msg)
                    if not flags & F_STREAM:
                        ok = True
                        return rep
                    if first:
                        first = False
                        s.settimeout(gap_t)
                    yield rep
        finally:
            self._streaming = False
            if not ok:
                self._drop()

    def close(self):
        with self._lock:
            self._drop()


# ---------------------------------------------------------------------------
# server-side connection loop: handshake + dedup + error replies
# ---------------------------------------------------------------------------

_FRESH = object()


_NULL_SCOPE = contextlib.nullcontext()


def _reply_nbytes(obj) -> int:
    """Rough retained size of a cached reply (arrays dominate)."""
    if isinstance(obj, np.ndarray):
        return obj.nbytes + 64
    if isinstance(obj, dict):
        return 64 + sum(_reply_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return 64 + sum(_reply_nbytes(v) for v in obj)
    return 64


class DedupCache:
    """Request-id -> reply memo for mutating ops (exactly-once across
    client retries). `begin` parks duplicate ids that race an in-flight
    original; entries are evicted FIFO past `capacity` entries or
    `max_bytes` of retained reply payload (the heter dense tier caches
    gradient-bundle replies — an entry-count bound alone would retain
    gigabytes)."""

    def __init__(self, capacity: int = 65536,
                 max_bytes: int = 256 * (1 << 20)):
        self.capacity = capacity
        self.max_bytes = max_bytes
        self._cond = threading.Condition()
        self._done: dict[int, object] = {}
        self._order: list[int] = []
        self._bytes = 0
        # newest committed req_id per client token (req_id >> 32): a
        # client serializes its calls, so only its LATEST request can
        # be mid-retry — protecting that one entry per client from
        # eviction closes the evicted-while-retrying double-apply
        # window at O(#clients) extra retention. The token set itself
        # is FIFO-bounded (first-seen order) so weeks of client churn
        # cannot pin unbounded replies; an expelled token's entry just
        # becomes normally evictable again.
        self._newest: dict[int, int] = {}
        self._token_order: list[int] = []
        self.token_capacity = 4096
        self._inflight: set[int] = set()

    def begin(self, req_id: int):
        """Returns the cached reply for a duplicate, or _FRESH (and
        marks the id in-flight) for a first arrival."""
        with self._cond:
            while True:
                if req_id in self._done:
                    return self._done[req_id]
                if req_id not in self._inflight:
                    self._inflight.add(req_id)
                    return _FRESH
                if not self._cond.wait(timeout=600):
                    raise TimeoutError(
                        f"duplicate request {req_id:#x} stuck behind an "
                        f"in-flight original")

    def commit(self, req_id: int, reply):
        with self._cond:
            self._inflight.discard(req_id)
            if req_id not in self._done:
                self._done[req_id] = reply
                self._order.append(req_id)
                self._bytes += _reply_nbytes(reply)
                token = req_id >> 32
                if token not in self._newest:
                    self._token_order.append(token)
                    while len(self._token_order) > self.token_capacity:
                        self._newest.pop(self._token_order.pop(0),
                                         None)
                self._newest[token] = req_id
                # evict FIFO past the entry/byte bound, but never a
                # client's newest entry — that one may be mid-retry
                scanned = 0
                while scanned < len(self._order) and \
                        (len(self._order) > self.capacity
                         or self._bytes > self.max_bytes):
                    old = self._order.pop(0)
                    if self._newest.get(old >> 32) == old:
                        self._order.append(old)  # protected; keep
                        scanned += 1
                        continue
                    gone = self._done.pop(old, None)
                    if gone is not None:
                        self._bytes -= _reply_nbytes(gone)
            self._cond.notify_all()

    def abort(self, req_id: int):
        with self._cond:
            self._inflight.discard(req_id)
            self._cond.notify_all()

    # -- snapshot support ----------------------------------------------
    def export(self) -> tuple[np.ndarray, list[bytes]]:
        with self._cond:
            ids = np.array(self._order, np.uint64)
            blobs = [encode_body(self._done[i]) for i in self._order]
        return ids, blobs

    def import_(self, ids: np.ndarray, blobs: list[bytes]):
        with self._cond:
            self._done.clear()
            self._order = []
            self._bytes = 0
            self._newest = {}
            self._token_order = []
            for i, blob in zip(ids.tolist(), blobs):
                reply = decode_body(blob)
                self._done[int(i)] = reply
                self._order.append(int(i))
                self._bytes += _reply_nbytes(reply)
                if (int(i) >> 32) not in self._newest:
                    self._token_order.append(int(i) >> 32)
                self._newest[int(i) >> 32] = int(i)
            self._cond.notify_all()


class RpcServerState:
    """Per-server transport state shared by all connection handlers."""

    def __init__(self, read_ops=frozenset(), secret: str | None = None,
                 dedup_capacity: int = 65536, after_commit=None,
                 commit_scope=None, after_retry=None,
                 expose_req_id: bool = False):
        self.read_ops = frozenset(read_ops)
        # inject the wire request id into the skeleton as "_req_id"
        # before dispatch: the serving router pins its DOWNSTREAM call
        # ids to the upstream id so a failover replay carries the same
        # identity on whichever replica serves it
        self.expose_req_id = bool(expose_req_id)
        self.secret = secret if secret is not None \
            else os.environ.get("PADDLE_PS_SECRET")
        self.dedup = DedupCache(dedup_capacity)
        # called with the op name when a MUTATING request is answered
        # from the dedup cache (client retry): the original dispatch may
        # have died between commit and its after_commit side effect, so
        # this is the hook's chance to finish pending persistence. It
        # must be idempotent and must NOT count a new mutation.
        self.after_retry = after_retry
        # called with the op name after a mutating op was dispatched and
        # its dedup entry recorded, BEFORE the reply is sent — the
        # snapshot hook runs here so a post-snapshot crash still yields
        # exactly-once on retry
        self.after_commit = after_commit
        # optional op -> lock/context-manager hook: when set, dispatch
        # + dedup.commit + after_commit run inside it, so a concurrent
        # snapshot export can never observe an applied mutation whose
        # dedup id is missing (or vice versa). Only ops whose dispatch
        # cannot block should return a scope — a barrier op waiting on
        # straggler trainers inside a shared lock would stall the shard
        self.commit_scope = commit_scope
        # optional (op, req, req_id, reply) hook called INSIDE the
        # commit scope right after dedup.commit — the WAL tier journals
        # the mutation (touched rows + request id) here, so a record is
        # on disk before the reply leaves and replay order matches
        # apply order
        self.journal = None


def _drain_stream(sock: socket.socket, gen, req_id: int):
    """Send every object a generator dispatch yields as an F_STREAM
    frame; its return value is the final reply. A dead client surfaces
    as a ConnectionError from the frame send — the generator is closed
    (GeneratorExit at its yield point lets the dispatcher cancel
    whatever produced the stream) and the error propagates like any
    dispatch failure."""
    try:
        while True:
            try:
                item = next(gen)
            except StopIteration as stop:
                return stop.value if stop.value is not None else {}
            send_frame(sock, item, req_id=req_id, flags=F_STREAM,
                       side="server")
    finally:
        gen.close()


def serve_connection(sock: socket.socket, dispatch, state: RpcServerState):
    """One connection's request loop. Application errors become error
    frames; transport errors end the connection (the client's retry
    path owns recovery). A dispatch that returns a GENERATOR streams:
    yielded objects go out as F_STREAM frames, the generator's return
    value is the final (dedup-memoised) reply."""
    try:
        server_handshake(sock, state.secret)
        while True:
            req, req_id, _flags, _n = recv_frame(sock, side="server")
            # re-read the injector each request: a chaos drill that
            # (re)arms the knobs mid-run must hit connections that
            # were already open (send_frame reads it per frame too)
            inj = injector()
            armed = inj.count_request() if inj.active else False
            if inj.active:
                inj.maybe_kill("recv", armed)
            op = req.get("op") if isinstance(req, dict) else None
            # wire-carried trace id (TRACE_KEY in the skeleton):
            # stripped before dispatch, re-rooted as this side's span
            # context so handler-side spans join the caller's trace
            wire_tid = req.pop(TRACE_KEY, None) \
                if isinstance(req, dict) else None
            if state.expose_req_id and isinstance(req, dict):
                req["_req_id"] = req_id
            _SERVER_REQS.labels(op=op or "?").inc()
            _flight.record("rpc", "server_request", trace_id=wire_tid,
                           op=op or "?", req_id=req_id)
            mutating = op not in state.read_ops
            if mutating and req_id:
                cached = state.dedup.begin(req_id)
                if cached is not _FRESH:
                    _SERVER_DEDUP_HITS.labels(op=op or "?").inc()
                    if state.after_retry is not None:
                        state.after_retry(op)
                    if inj.active:
                        inj.maybe_kill("reply", armed)
                    send_frame(sock, cached, req_id=req_id,
                               side="server")
                    continue
            scope = state.commit_scope(op) \
                if state.commit_scope is not None else None
            err = None
            with scope if scope is not None else _NULL_SCOPE:
                try:
                    with _tracing.span(f"rpc.server.{op or 'raw'}",
                                       trace_id=wire_tid,
                                       op=op or "?"):
                        rep = dispatch(req)
                        if isinstance(rep, types.GeneratorType):
                            rep = _drain_stream(sock, rep, req_id)
                except Exception as e:
                    # application/dispatch failure (including barrier
                    # timeouts): report as an error frame instead of
                    # silently killing the connection
                    if mutating and req_id:
                        state.dedup.abort(req_id)
                    err = {"error": f"{type(e).__name__}: {e}",
                           "kind": "app"}
                else:
                    if mutating and req_id:
                        state.dedup.commit(req_id, rep)
                        if state.journal is not None:
                            # WAL write-ahead: rows + request id land
                            # on disk inside the commit scope, so a
                            # crash-restore replays this mutation AND
                            # dedups its retry (exactly-once survives)
                            state.journal(op, req, req_id, rep)
            if err is not None:
                _SERVER_ERRORS.labels(op=op or "?").inc()
                _flight.record("rpc", "server_error",
                               trace_id=wire_tid, op=op or "?",
                               error=err.get("error"))
                send_frame(sock, err, req_id=req_id, flags=F_ERROR,
                           side="server")
                continue
            if mutating and state.after_commit is not None:
                # outside the commit scope (a snapshot's disk write
                # must not stall other pushes on the commit lock) but
                # before the reply: a crash in here still resolves to
                # exactly-once — the mutation IS committed, so the
                # client's retry lands on the dedup cache. Failures
                # (e.g. snapshot disk error) propagate and close the
                # connection for the same reason.
                state.after_commit(op)
            if inj.active:
                inj.maybe_kill("reply", armed)
            send_frame(sock, rep, req_id=req_id, side="server")
    except (PSAuthError, WireError, ConnectionError, OSError):
        pass
    finally:
        try:
            sock.close()
        except OSError:
            pass
