"""Fault-tolerant multiplexed RPC layer for the PS/heter/serving tier.

Replaces the seed's length-prefixed-pickle transport with a data-only
wire format plus client retry and server dedup. Reference analog: the
brpc channel options (timeout_ms / max_retry / backoff) and the
correlation-id multiplexing of its single-connection-many-RPCs model,
re-expressed as a dependency-free protocol:

  frame   := header || body
  header  := magic u16 | ver u8 | flags u8 | req_id u64 | crc u32
             | body_len u64                      (24 bytes, little-endian)
  body    := skel_len u32 | skeleton(JSON) | segment*
  segment := dtype u8 | ndim u8 | dims i64*ndim | raw row-major bytes

The skeleton is plain JSON (dict/list/str/number/bool/null) where every
ndarray was replaced by {"__nd__": k}; segments carry the arrays in
order. Decoding therefore never evaluates attacker-controlled code —
`json.loads` plus `np.frombuffer` against a dtype whitelist — unlike the
pickle path this replaces (ADVICE: RCE if bound beyond localhost).

Integrity/auth:
  * crc32 over the body rejects corrupted frames (fault tolerance, not
    security — CRC is not a MAC).
  * optional shared-secret handshake: when PADDLE_PS_SECRET is set on
    the server, every connection must answer an HMAC-SHA256 challenge
    before the first request. See docs/PS_WIRE_PROTOCOL.md for the
    remaining trusted-network assumptions.

Multiplexing (PR 11): every frame — request, reply, F_STREAM push,
F_CANCEL — carries its request id in the header, so ONE socket
interleaves many concurrent calls and replies may arrive out of order.
A channel runs a writer thread (draining a send queue) and a reader
thread (demuxing frames to per-call waiters by request id); callers
never touch the socket. `RpcClient` keeps a small per-endpoint channel
pool (PADDLE_TPU_RPC_POOL_SIZE) with a per-channel in-flight cap
(PADDLE_TPU_RPC_MAX_INFLIGHT); a streamed call no longer monopolizes a
connection. PADDLE_TPU_RPC_MUX=0 restores the legacy
one-call-per-channel discipline (same pool, exclusive channel per call,
classic copying reads) for A/B benchmarks.

Zero-copy receive: the mux reader lands each body in a pooled buffer
via ``recv_into`` and decodes ndarray segments as views into it — no
chunk-assembly copy. The buffer returns to the pool once no decoded
array references it (``BufferPool``). Transport-level copies are
counted on ``paddle_tpu_rpc_mux_bytes_copied_total`` (the mux path
copies only the header + JSON skeleton; the legacy path copies every
body byte), which is the proof the hot PS pull path stopped copying.

Corruption scope: under multiplexing a corrupt BODY on an intact header
poisons only its own request id — the reader has consumed exactly
body_len bytes, the stream stays framed, and concurrent calls on the
socket are untouched (the server answers that id with a retryable
``kind="wire"`` error frame; the client fails just that call). A
corrupt HEADER still desyncs the stream and kills the connection.

Client semantics (`RpcClient.call`):
  * per-request deadline + per-attempt timeout,
  * exponential backoff with jitter, bounded retries/reconnects,
  * a stable request id across retries; the server dedups mutating ops
    by id, so a retried gradient push is applied exactly once. Callers
    that own failover across SERVERS (the serving router) can pin the
    id themselves via ``req_id=`` so a replay on whichever replica —
    original or survivor — carries the same identity.

Server-push streaming: a dispatch function may return a GENERATOR.
`serve_connection` then sends every yielded object as an ``F_STREAM``
frame (same request id) and the generator's return value as the normal
final reply — which is what the dedup cache memoises, so a retried
streamed op is answered with the final frame only. Clients consume the
pushed frames via ``call(..., on_stream=fn)`` or ``call_stream``; the
per-attempt timeout bounds the INTER-FRAME gap per stream, which is how
the serving router detects a replica wedged mid-generation
(docs/SERVING.md). A client that abandons a stream sends ``F_CANCEL``
for that id; the server raises GeneratorExit into the dispatch
generator so whatever produced the stream is cancelled — the connection
itself survives (it is shared).
"""
from __future__ import annotations

import contextlib
import hmac
import hashlib
import itertools
import json
import os
import queue
import random
import socket
import struct
import sys
import threading
import time
import types
import zlib
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ....observability import (flight as _flight, registry as _obs,
                               tracing as _tracing)
from .fault_injection import injector

__all__ = [
    "WireError", "PSAuthError", "PSRemoteError", "PSDeadlineError",
    "encode_body", "decode_body", "send_frame", "recv_frame",
    "TransportStats", "RpcClient", "DedupCache", "RpcServerState",
    "serve_connection", "PROTOCOL_VERSION", "TRACE_KEY", "F_STREAM",
    "F_CANCEL", "BufferPool",
]

PROTOCOL_VERSION = 1
_MAGIC = 0x7053                      # "Sp" — PS rpc

# transport telemetry on the process-wide registry. The skeleton may
# carry a `_trace_id` field (injected by RpcClient.call, stripped by
# serve_connection before dispatch) so one request is followable
# worker -> PS server and frontend -> engine across processes.
TRACE_KEY = "_trace_id"
_CLIENT_EVENTS = _obs.counter(
    "paddle_tpu_rpc_client_events_total",
    "client transport events (requests/retries/timeouts/...)",
    ["event"])
_CLIENT_BYTES = _obs.counter(
    "paddle_tpu_rpc_client_bytes_total",
    "client wire bytes by direction", ["direction"])
_CLIENT_LATENCY = _obs.histogram(
    "paddle_tpu_rpc_client_latency_seconds",
    "successful call() round-trip latency incl. retries", ["op"])
_SERVER_REQS = _obs.counter(
    "paddle_tpu_rpc_server_requests_total",
    "requests received by serve_connection", ["op"])
_SERVER_ERRORS = _obs.counter(
    "paddle_tpu_rpc_server_errors_total",
    "dispatch failures answered with an error frame", ["op"])
_SERVER_DEDUP_HITS = _obs.counter(
    "paddle_tpu_rpc_server_dedup_hits_total",
    "mutating requests answered from the dedup cache (client retries)",
    ["op"])
# mux-transport telemetry (PR 11): the in-flight/pool gauges size the
# channel fan-out, bytes-copied proves the zero-copy pull path, and the
# out-of-order counter proves replies genuinely interleave.
_MUX_INFLIGHT = _obs.gauge(
    "paddle_tpu_rpc_mux_inflight",
    "in-flight calls multiplexed across one client's channel pool",
    ["endpoint"])
_MUX_CHANNELS = _obs.gauge(
    "paddle_tpu_rpc_mux_channels",
    "open channels in a client's per-endpoint pool", ["endpoint"])
_MUX_BYTES_COPIED = _obs.counter(
    "paddle_tpu_rpc_mux_bytes_copied_total",
    "receive-path bytes memcpy'd by the transport (mux: header+skeleton"
    " only; legacy: every body byte is assembled through a copy)",
    ["path"])
_MUX_OUT_OF_ORDER = _obs.counter(
    "paddle_tpu_rpc_mux_out_of_order_total",
    "replies that completed a call that was not the oldest in flight "
    "on its channel")
_MUX_ORPHANS = _obs.counter(
    "paddle_tpu_rpc_mux_orphan_frames_total",
    "frames whose request id had no waiter (late reply after a timeout"
    " or an abandoned stream)")
_MUX_FRAME_ERRORS = _obs.counter(
    "paddle_tpu_rpc_mux_frame_errors_total",
    "body-local frame failures contained to one request id", ["side"])
_HDR = struct.Struct("<HBBQIQ")      # magic, ver, flags, req_id, crc, len
HEADER_SIZE = _HDR.size
F_ERROR = 1
F_HANDSHAKE = 2
F_STREAM = 4                         # server-push frame; more follow
F_CANCEL = 8                         # client abandons this request id
_MAX_BODY = 1 << 31                  # sanity bound on a length field

_ND_KEY = "__nd__"

# dtype whitelist: receiving anything else is a wire error, never an
# object/pickle dtype
_DTYPES = [np.dtype(s) for s in (
    "float32", "float64", "float16", "int64", "int32", "int16", "int8",
    "uint64", "uint32", "uint16", "uint8", "bool")]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}


class WireError(ConnectionError):
    """Malformed/corrupt frame — the connection is no longer trusted."""


class PSAuthError(RuntimeError):
    """Handshake failure. Not retryable."""


class PSRemoteError(RuntimeError):
    """The server dispatched the request and replied with an error."""


class PSDeadlineError(ConnectionError):
    """Retries/deadline exhausted without a successful round-trip."""


class _FrameError(Exception):
    """Body-local failure (bad crc / bad body) on an INTACT frame: the
    reader consumed exactly body_len bytes, so the stream is still
    framed and only this request id's call is poisoned."""

    def __init__(self, req_id: int, flags: int, msg: str):
        super().__init__(msg)
        self.req_id = req_id
        self.flags = flags


class _Cancelled(Exception):
    """Server-side: the client sent F_CANCEL (or died) mid-stream."""


# ---------------------------------------------------------------------------
# body codec: JSON skeleton + dtype/shape-tagged ndarray segments
# ---------------------------------------------------------------------------

def encode_body(obj) -> bytes:
    arrays: list[np.ndarray] = []

    def strip(o):
        if isinstance(o, np.ndarray):
            arrays.append(o)
            return {_ND_KEY: len(arrays) - 1}
        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, dict):
            return {str(k): strip(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [strip(v) for v in o]
        return o

    skel = json.dumps(strip(obj)).encode("utf-8")
    parts = [struct.pack("<I", len(skel)), skel]
    for a in arrays:
        a = np.ascontiguousarray(a)
        code = _DTYPE_CODE.get(a.dtype)
        if code is None:
            raise TypeError(
                f"dtype {a.dtype} is not wire-safe (whitelist: "
                f"{[str(d) for d in _DTYPES]})")
        parts.append(struct.pack("<BB", code, a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def _decode_body_ex(buf):
    """Core decoder over any buffer-protocol object (bytes for the
    legacy path, a read-only memoryview of a pooled buffer for the mux
    path — the ndarray segments become VIEWS into it, no copy).

    Returns (obj, n_arrays, copied): `n_arrays` tells the caller
    whether the source buffer is now referenced by live views (it must
    stay leased), `copied` is the bytes memcpy'd here (the JSON
    skeleton — json.loads needs a bytes object)."""
    if len(buf) < 4:
        raise WireError("body too short")
    (skel_len,) = struct.unpack_from("<I", buf, 0)
    if 4 + skel_len > len(buf):
        raise WireError("skeleton length exceeds body")
    try:
        skel = json.loads(bytes(buf[4:4 + skel_len]).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"bad skeleton: {e}") from None
    arrays: list[np.ndarray] = []
    off = 4 + skel_len
    while off < len(buf):
        if off + 2 > len(buf):
            raise WireError("truncated segment header")
        code, ndim = struct.unpack_from("<BB", buf, off)
        off += 2
        if code >= len(_DTYPES) or ndim > 16:
            raise WireError(f"bad segment tag ({code}, {ndim})")
        if off + 8 * ndim > len(buf):
            raise WireError("truncated segment dims")
        dims = struct.unpack_from(f"<{ndim}q", buf, off)
        off += 8 * ndim
        if any(d < 0 for d in dims):
            raise WireError(f"negative dim {dims}")
        dt = _DTYPES[code]
        # python-int product: immune to the int64 overflow a hostile
        # dims vector could use to slip past the bounds check
        count = 1
        for d in dims:
            count *= d
        nbytes = count * dt.itemsize if ndim else dt.itemsize
        if nbytes > len(buf) - off:
            raise WireError("segment data exceeds body")
        try:
            arr = np.frombuffer(buf, dt, count=nbytes // dt.itemsize,
                                offset=off).reshape(dims)
        except ValueError as e:
            raise WireError(f"bad segment geometry: {e}") from None
        arrays.append(arr)
        off += nbytes

    def build(o):
        if isinstance(o, dict):
            if set(o) == {_ND_KEY} and isinstance(o[_ND_KEY], int):
                k = o[_ND_KEY]
                if not 0 <= k < len(arrays):
                    raise WireError(f"dangling array ref {k}")
                return arrays[k]
            return {k: build(v) for k, v in o.items()}
        if isinstance(o, list):
            return [build(v) for v in o]
        return o

    return build(skel), len(arrays), 4 + skel_len


def decode_body(buf):
    obj, _n, _copied = _decode_body_ex(buf)
    return obj


# ---------------------------------------------------------------------------
# pooled receive buffers (zero-copy mux read path)
# ---------------------------------------------------------------------------

class BufferPool:
    """Size-classed pool of receive buffers for `recv_into`.

    A buffer whose decoded frame contained ndarray segments is LEASED:
    the arrays are views into it, so it cannot be reused until every
    view is gone. numpy keeps the underlying buffer referenced through
    the view chain, so a leased buffer is reclaimable exactly when its
    refcount drops back to the pool's own references — checked with
    `sys.getrefcount` on each acquire (pure CPython refcounting; no GC
    or finalizer dependency, so reuse can never race a live view)."""

    _MIN = 1 << 12

    def __init__(self, max_bytes: int = 64 * (1 << 20),
                 max_leases: int = 512):
        self.max_bytes = max_bytes
        self.max_leases = max_leases
        self._lock = threading.Lock()
        self._free: dict[int, list[bytearray]] = {}
        self._free_bytes = 0
        self._leased: list[bytearray] = []
        self.hits = 0
        self.misses = 0

    @classmethod
    def _cls_size(cls, n: int) -> int:
        size = cls._MIN
        while size < n:
            size <<= 1
        return size

    def _reclaim_locked(self):
        still = []
        for buf in self._leased:
            # refs while scanning: the list entry, the loop variable,
            # and getrefcount's argument == 3 when no view is left
            if sys.getrefcount(buf) <= 3:
                self._stash_locked(buf)
            else:
                still.append(buf)
        self._leased = still

    def _stash_locked(self, buf: bytearray):
        if self._free_bytes + len(buf) <= self.max_bytes:
            self._free.setdefault(len(buf), []).append(buf)
            self._free_bytes += len(buf)

    def acquire(self, n: int) -> bytearray:
        """A bytearray of some size class >= n (slice a memoryview to
        the exact length)."""
        size = self._cls_size(n)
        with self._lock:
            self._reclaim_locked()
            bucket = self._free.get(size)
            if bucket:
                self.hits += 1
                self._free_bytes -= size
                return bucket.pop()
            self.misses += 1
        return bytearray(size)

    def release(self, buf: bytearray):
        """Return a buffer no live view references (frames that decoded
        to pure-JSON bodies release immediately)."""
        with self._lock:
            self._stash_locked(buf)

    def lease(self, buf: bytearray):
        """Track a buffer still referenced by decoded array views; it
        rejoins the free list once they are all gone."""
        with self._lock:
            if len(self._leased) < self.max_leases:
                self._leased.append(buf)
            # else: forget it — plain GC takes it when the views die

    def stats(self) -> dict:
        with self._lock:
            return {"free_bytes": self._free_bytes,
                    "leased": len(self._leased),
                    "hits": self.hits, "misses": self.misses}


# one process-wide pool shared by every mux reader (client channels and
# server connections): PS pull replies and gradient pushes recycle the
# same few hot size classes
_BUFFER_POOL = BufferPool()


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def _hard_close(sock: socket.socket):
    """Tear a connection down so the PEER and every local thread see it
    NOW. ``close()`` alone is not enough on a multiplexed socket: a
    thread blocked in ``recv`` on the same socket pins the open file
    description, so the kernel keeps the connection alive and no FIN
    goes out until that recv returns — the other end then burns its
    full per-attempt timeout staring at a healthy-looking silent
    channel. ``shutdown`` acts on the file description itself: it sends
    the FIN and wakes blocked readers immediately."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _build_frame(obj, req_id: int = 0, flags: int = 0) -> bytes:
    body = encode_body(obj)
    return _HDR.pack(_MAGIC, PROTOCOL_VERSION, flags, req_id,
                     zlib.crc32(body), len(body)) + body


def send_frame(sock: socket.socket, obj, req_id: int = 0,
               flags: int = 0, side: str | None = None) -> int:
    frame = _build_frame(obj, req_id, flags)
    inj = injector()
    if inj.active:
        frame, action = inj.mangle(frame, HEADER_SIZE, side,
                                   req_id=req_id)
        if action == "drop":
            _hard_close(sock)
            raise ConnectionError("fault-injected frame drop")
        if action == "truncate":
            try:
                sock.sendall(frame[:max(len(frame) // 2, 1)])
            finally:
                _hard_close(sock)
            raise ConnectionError("fault-injected frame truncation")
        if action == "skip":
            return 0        # granular single-frame drop: frame vanishes
    sock.sendall(frame)
    return len(frame)


def _recvn(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket, side: str | None = None):
    """Blocking copying read (handshakes, legacy channels, direct
    protocol tests). Returns (obj, req_id, flags, frame_bytes). Raises
    WireError on a frame that fails validation — the stream is
    desynced, the caller must close the connection."""
    hdr = _recvn(sock, HEADER_SIZE)
    magic, ver, flags, req_id, crc, body_len = _HDR.unpack(hdr)
    if magic != _MAGIC:
        raise WireError(f"bad magic 0x{magic:04x}")
    if ver != PROTOCOL_VERSION:
        raise WireError(f"protocol version {ver} != {PROTOCOL_VERSION}")
    if body_len > _MAX_BODY:
        raise WireError(f"body length {body_len} exceeds bound")
    body = _recvn(sock, body_len)
    if zlib.crc32(body) != crc:
        raise WireError("crc mismatch (corrupt frame)")
    # the bytearray-chunk assembly + bytes() above copied the whole body
    _MUX_BYTES_COPIED.labels(path="legacy").inc(HEADER_SIZE + body_len)
    return decode_body(body), req_id, flags, HEADER_SIZE + body_len


def _recv_into(sock: socket.socket, mv: memoryview):
    got = 0
    while got < len(mv):
        n = sock.recv_into(mv[got:])
        if not n:
            raise ConnectionError("peer closed")
        got += n


def _read_frame_mux(sock: socket.socket, pool: BufferPool,
                    hdr_buf: bytearray):
    """Zero-copy frame read: body lands in a pooled buffer via
    recv_into; ndarray segments decode as views into it (the buffer is
    leased until they die). Returns (obj, req_id, flags, nbytes).

    Raises WireError/ConnectionError for stream-fatal failures (bad
    header, EOF) and _FrameError for body-local ones (bad crc, bad
    body) — the frame was fully consumed, the stream is still synced,
    only that request id is poisoned."""
    _recv_into(sock, memoryview(hdr_buf))
    magic, ver, flags, req_id, crc, body_len = _HDR.unpack(hdr_buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic 0x{magic:04x}")
    if ver != PROTOCOL_VERSION:
        raise WireError(f"protocol version {ver} != {PROTOCOL_VERSION}")
    if body_len > _MAX_BODY:
        raise WireError(f"body length {body_len} exceeds bound")
    buf = pool.acquire(body_len)
    view = memoryview(buf)[:body_len]
    _recv_into(sock, view)
    if zlib.crc32(view) != crc:
        pool.release(buf)
        raise _FrameError(req_id, flags, "crc mismatch (corrupt frame)")
    try:
        obj, n_arrays, copied = _decode_body_ex(view.toreadonly())
    except WireError as e:
        pool.release(buf)
        raise _FrameError(req_id, flags, str(e)) from None
    if n_arrays:
        pool.lease(buf)
    else:
        pool.release(buf)
    _MUX_BYTES_COPIED.labels(path="mux").inc(HEADER_SIZE + copied)
    return obj, req_id, flags, HEADER_SIZE + body_len


def _send_mux(sock: socket.socket, frame: bytes, side: str,
              req_id: int, requeue) -> int:
    """Writer-thread send with fault injection. Granular single-frame
    faults (by request id) consume/delay ONE frame without touching the
    channel; the legacy probabilistic knobs keep their connection-death
    semantics. Returns bytes sent; raises ConnectionError when the
    channel must die."""
    inj = injector()
    if inj.active:
        act = inj.frame_fault(req_id, side)
        if act is not None:
            kind, arg = act
            if kind == "drop":
                return 0                 # this frame silently vanishes
            if kind == "delay":
                threading.Timer(arg, requeue,
                                args=(frame, req_id)).start()
                return 0
            if kind == "corrupt" and len(frame) > HEADER_SIZE:
                buf = bytearray(frame)
                buf[HEADER_SIZE] ^= 0xFF
                frame = bytes(buf)
        frame, action = inj.mangle(frame, HEADER_SIZE, side)
        if action == "drop":
            # _hard_close, not close(): the connection's reader thread
            # is blocked in recv on this socket — a bare close would
            # leave the kernel connection up and the peer waiting out
            # its whole timeout on a silent channel
            _hard_close(sock)
            raise ConnectionError("fault-injected frame drop")
        if action == "truncate":
            try:
                sock.sendall(frame[:max(len(frame) // 2, 1)])
            finally:
                _hard_close(sock)
            raise ConnectionError("fault-injected frame truncation")
        if action == "skip":
            return 0
    sock.sendall(frame)
    return len(frame)


# ---------------------------------------------------------------------------
# handshake: protocol version + optional HMAC shared secret
# ---------------------------------------------------------------------------

def _mac(secret: str, nonce: str) -> str:
    return hmac.new(secret.encode(), nonce.encode(),
                    hashlib.sha256).hexdigest()


def server_handshake(sock: socket.socket, secret: str | None):
    nonce = os.urandom(16).hex() if secret else None
    send_frame(sock, {"ver": PROTOCOL_VERSION, "nonce": nonce},
               flags=F_HANDSHAKE)
    reply, _rid, flags, _n = recv_frame(sock)
    if not flags & F_HANDSHAKE:
        raise WireError("expected handshake reply")
    if secret is not None:
        mac = reply.get("mac") if isinstance(reply, dict) else None
        if not (isinstance(mac, str)
                and hmac.compare_digest(mac, _mac(secret, nonce))):
            send_frame(sock, {"error": "authentication failed",
                              "kind": "auth"}, flags=F_ERROR)
            raise PSAuthError("client failed the PADDLE_PS_SECRET "
                              "challenge")
    send_frame(sock, {"ok": True}, flags=F_HANDSHAKE)


def client_handshake(sock: socket.socket, secret: str | None):
    hello, _rid, flags, _n = recv_frame(sock)
    if not flags & F_HANDSHAKE or not isinstance(hello, dict):
        raise WireError("expected handshake hello")
    if hello.get("ver") != PROTOCOL_VERSION:
        raise PSAuthError(
            f"server protocol version {hello.get('ver')} != "
            f"{PROTOCOL_VERSION}")
    nonce = hello.get("nonce")
    if nonce is not None and secret is None:
        raise PSAuthError(
            "server requires a shared secret — set PADDLE_PS_SECRET")
    mac = _mac(secret, nonce) if nonce is not None else None
    send_frame(sock, {"mac": mac}, flags=F_HANDSHAKE)
    ok, _rid, flags, _n = recv_frame(sock)
    if flags & F_ERROR:
        raise PSAuthError(str(ok.get("error", "handshake rejected"))
                          if isinstance(ok, dict) else "rejected")
    if not flags & F_HANDSHAKE:
        raise WireError("expected handshake ack")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class TransportStats:
    """Thread-safe transport counters, shared across a client's
    per-endpoint connections (tests/benchmarks read these)."""

    _FIELDS = ("requests", "retries", "reconnects", "timeouts",
               "corrupt_frames", "remote_errors", "deadline_exceeded")

    def __init__(self):
        self._lock = threading.Lock()
        self.bytes_out = 0
        self.bytes_in = 0
        for f in self._FIELDS:
            setattr(self, f, 0)

    def add(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        # mirror into the process-wide registry (PSClient.stats keeps
        # its exact per-client surface; /metrics shows the aggregate)
        _CLIENT_EVENTS.labels(event=field).inc(n)

    def add_bytes(self, n_out: int, n_in: int):
        with self._lock:
            self.bytes_out += n_out
            self.bytes_in += n_in
        _CLIENT_BYTES.labels(direction="out").inc(n_out)
        _CLIENT_BYTES.labels(direction="in").inc(n_in)

    def as_dict(self) -> dict:
        with self._lock:
            d = {f: getattr(self, f) for f in self._FIELDS}
            d["bytes_out"] = self.bytes_out
            d["bytes_in"] = self.bytes_in
            return d


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


_WAITER_DEAD = "dead"      # channel died; payload = exception
_WAITER_REPLY = "reply"    # final reply;  payload = decoded object
_WAITER_STREAM = "stream"  # F_STREAM push; payload = decoded object
_WAITER_ERRFRAME = "err"   # F_ERROR reply; payload = decoded object
_WAITER_WIRE = "wire"      # body-local corruption; payload = message


class _Channel:
    """One multiplexed connection: a writer thread drains a send queue,
    a reader thread demuxes incoming frames to per-call waiter queues
    by request id. Neither the caller nor any lock ever touches the
    socket directly, so many calls interleave on one socket and a
    reply completes whichever call it belongs to — in any order.

    ``zero_copy=False`` (legacy A/B mode) reads with the classic
    copying `recv_frame` and keeps PR-1's corruption semantics (a bad
    frame kills the connection)."""

    def __init__(self, client: "RpcClient", connect_timeout: float,
                 zero_copy: bool = True):
        self.client = client
        self.endpoint = client.endpoint
        self.zero_copy = zero_copy
        self.dead = False
        self.inflight = 0            # guarded by client._pool_cond
        self.last_rx = time.monotonic()
        self._wlock = threading.Lock()   # waiter tables only — no IO
        self._waiters: dict[int, queue.SimpleQueue] = {}
        self._order: dict[int, None] = {}
        self._sendq: queue.SimpleQueue = queue.SimpleQueue()
        host, port = self.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)),
                                     timeout=connect_timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(connect_timeout)
            client_handshake(s, client.secret)
            # blocking from here on: per-call timeouts live at the
            # waiter queues; a wedged channel is killed by the caller
            # when last_rx stops advancing
            s.settimeout(None)
        except BaseException:
            _hard_close(s)
            raise
        self.sock = s
        threading.Thread(target=self._writer, daemon=True,
                         name=f"rpc-mux-w-{self.endpoint}").start()
        threading.Thread(target=self._reader, daemon=True,
                         name=f"rpc-mux-r-{self.endpoint}").start()

    # -- caller API -----------------------------------------------------
    def register(self, req_id: int) -> queue.SimpleQueue:
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._wlock:
            if self.dead:
                raise ConnectionError("mux channel is closed")
            self._waiters[req_id] = q
            self._order[req_id] = None
        return q

    def deregister(self, req_id: int):
        with self._wlock:
            self._waiters.pop(req_id, None)
            self._order.pop(req_id, None)

    def send(self, frame: bytes, req_id: int):
        if self.dead:
            raise ConnectionError("mux channel is closed")
        self._sendq.put((frame, req_id))

    def close(self):
        self._kill(ConnectionError("mux channel closed"))

    # -- threads --------------------------------------------------------
    def _requeue(self, frame: bytes, req_id: int):
        # a fault-delayed frame re-enters the queue; later frames have
        # already overtaken it (that is the point of the fault)
        if not self.dead:
            self._sendq.put((frame, req_id))

    def _writer(self):
        while True:
            item = self._sendq.get()
            if item is None:
                return
            frame, rid = item
            try:
                n = _send_mux(self.sock, frame, "client", rid,
                              self._requeue)
            except Exception as e:
                self._kill(e)
                return
            if n:
                self.client.stats.add_bytes(n, 0)

    def _reader(self):
        hdr = bytearray(HEADER_SIZE)
        try:
            while True:
                if self.zero_copy:
                    try:
                        obj, rid, flags, n = _read_frame_mux(
                            self.sock, self.client.pool, hdr)
                    except _FrameError as fe:
                        # intact frame, corrupt body: fail ONLY the
                        # call it belongs to; the channel lives on
                        self.last_rx = time.monotonic()
                        _MUX_FRAME_ERRORS.labels(side="client").inc()
                        self._deliver(fe.req_id, (_WAITER_WIRE,
                                                  str(fe)))
                        continue
                else:
                    obj, rid, flags, n = recv_frame(self.sock,
                                                    side="client")
                self.last_rx = time.monotonic()
                self.client.stats.add_bytes(0, n)
                if flags & F_STREAM:
                    self._deliver(rid, (_WAITER_STREAM, obj))
                elif flags & F_ERROR:
                    self._deliver(rid, (_WAITER_ERRFRAME, obj))
                else:
                    self._note_completion_order(rid)
                    self._deliver(rid, (_WAITER_REPLY, obj))
        except Exception as e:
            self._kill(e)

    def _note_completion_order(self, rid: int):
        with self._wlock:
            if rid in self._order and next(iter(self._order)) != rid:
                out_of_order = True
            else:
                out_of_order = False
        if out_of_order:
            _MUX_OUT_OF_ORDER.inc()

    def _deliver(self, rid: int, event):
        with self._wlock:
            q = self._waiters.get(rid)
        if q is None:
            _MUX_ORPHANS.inc()
        else:
            q.put(event)

    def _kill(self, exc: Exception):
        with self._wlock:
            if self.dead:
                return
            self.dead = True
            waiters = list(self._waiters.values())
            self._waiters.clear()
            self._order.clear()
        for q in waiters:
            q.put((_WAITER_DEAD, exc))
        # _hard_close so the reader thread (blocked in recv on this
        # socket) wakes and the server sees the FIN immediately
        _hard_close(self.sock)
        self._sendq.put(None)
        self.client._on_channel_death(self)


class RpcClient:
    """One endpoint's fault-tolerant multiplexed channel pool: lazy
    connect + handshake, per-request deadline, exponential backoff with
    jitter, bounded retries, and stable request ids for server-side
    dedup. Safe for concurrent use from many threads — calls (including
    streams) interleave over the pooled channels."""

    def __init__(self, endpoint: str, stats: TransportStats | None = None,
                 secret: str | None = None,
                 timeout: float | None = None,
                 deadline: float | None = None,
                 max_retries: int | None = None,
                 backoff: float | None = None,
                 backoff_max: float = 2.0,
                 pool_size: int | None = None,
                 max_inflight: int | None = None,
                 mux: bool | None = None):
        self.endpoint = endpoint
        self.stats = stats if stats is not None else TransportStats()
        self.secret = secret if secret is not None \
            else os.environ.get("PADDLE_PS_SECRET")
        self.timeout = timeout if timeout is not None \
            else _env_float("PADDLE_PS_TIMEOUT", 60.0)
        self.deadline = deadline if deadline is not None \
            else _env_float("PADDLE_PS_DEADLINE", 600.0)
        self.max_retries = max_retries if max_retries is not None \
            else int(_env_float("PADDLE_PS_RETRIES", 64))
        self.backoff = backoff if backoff is not None \
            else _env_float("PADDLE_PS_BACKOFF", 0.05)
        self.backoff_max = backoff_max
        self.pool_size = pool_size if pool_size is not None \
            else max(1, int(_env_float("PADDLE_TPU_RPC_POOL_SIZE", 2)))
        self.max_inflight = max_inflight if max_inflight is not None \
            else max(1, int(_env_float("PADDLE_TPU_RPC_MAX_INFLIGHT",
                                       128)))
        if mux is None:
            mux = os.environ.get("PADDLE_TPU_RPC_MUX", "1") \
                not in ("0", "false", "no")
        self.mux = bool(mux)
        self.pool = _BUFFER_POOL
        self._pool_cond = threading.Condition()
        self._channels: list[_Channel] = []
        self._connecting = 0
        self._closed = False
        self._ever_connected = False
        self._had_loss = False
        # request ids stay unique across client restarts of THIS process
        # but not across client processes — a 32-bit random token
        # namespaces the 32-bit sequence
        self._token = int.from_bytes(os.urandom(4), "little")
        self._seq = itertools.count(1)

    def _next_id(self) -> int:
        return (self._token << 32) | (next(self._seq) & 0xFFFFFFFF)

    # -- channel pool ---------------------------------------------------
    def _set_gauges_locked(self):
        _MUX_CHANNELS.labels(endpoint=self.endpoint).set(
            len(self._channels))
        _MUX_INFLIGHT.labels(endpoint=self.endpoint).set(
            sum(c.inflight for c in self._channels))

    def _acquire_channel(self, wait_timeout: float,
                         exclusive: bool) -> _Channel:
        """A live channel with a free call slot. ``exclusive`` (legacy
        one-call-per-channel mode) reserves the whole channel. Blocks
        up to wait_timeout when the pool is saturated; connects a new
        channel (outside any lock) while the pool is below size."""
        deadline_ts = time.monotonic() + wait_timeout
        with self._pool_cond:
            while True:
                if self._closed:
                    raise ConnectionError("client closed")
                if any(c.dead for c in self._channels):
                    self._channels = [c for c in self._channels
                                      if not c.dead]
                cap = 1 if exclusive else self.max_inflight
                live = [c for c in self._channels if c.inflight < cap]
                if live:
                    ch = min(live, key=lambda c: c.inflight)
                    ch.inflight += 1
                    self._set_gauges_locked()
                    return ch
                if len(self._channels) + self._connecting \
                        < self.pool_size:
                    self._connecting += 1
                    break
                left = deadline_ts - time.monotonic()
                if left <= 0:
                    raise socket.timeout(
                        f"{self.endpoint}: all {self.pool_size} "
                        f"channel(s) at capacity")
                self._pool_cond.wait(left)
        # connect OUTSIDE the pool lock: a slow handshake must not
        # stall calls that could ride an existing channel
        try:
            ch = _Channel(self, min(5.0, max(wait_timeout, 0.1)),
                          zero_copy=self.mux)
        except BaseException:
            with self._pool_cond:
                self._connecting -= 1
                self._pool_cond.notify_all()
            raise
        with self._pool_cond:
            self._connecting -= 1
            if self._closed:
                self._pool_cond.notify_all()
                ch_dead = ch
            else:
                if self._ever_connected and self._had_loss:
                    self.stats.add("reconnects")
                    self._had_loss = False
                self._ever_connected = True
                ch.inflight = 1
                self._channels.append(ch)
                self._set_gauges_locked()
                self._pool_cond.notify_all()
                return ch
        ch_dead.close()
        raise ConnectionError("client closed")

    def _release_channel(self, ch: _Channel):
        with self._pool_cond:
            ch.inflight = max(0, ch.inflight - 1)
            self._set_gauges_locked()
            self._pool_cond.notify_all()

    def _on_channel_death(self, ch: _Channel):
        with self._pool_cond:
            self._had_loss = True
            if ch in self._channels:
                self._channels.remove(ch)
            self._set_gauges_locked()
            self._pool_cond.notify_all()

    def _drop(self):
        """Close every pooled channel (tests / server-restart paths);
        the next call reconnects."""
        with self._pool_cond:
            chans = list(self._channels)
            self._channels = []
            self._had_loss = True
            self._set_gauges_locked()
            self._pool_cond.notify_all()
        for c in chans:
            c.close()

    def close(self):
        with self._pool_cond:
            self._closed = True
        self._drop()
        for m in (_MUX_CHANNELS, _MUX_INFLIGHT):
            m.remove_matching(endpoint=self.endpoint)

    # -- calls ----------------------------------------------------------
    def call(self, req, timeout: float | None = None,
             deadline: float | None = None, on_stream=None,
             req_id: int | None = None,
             max_retries: int | None = None):
        """One request/reply round-trip; retried with the same request
        id until success, the deadline, or the retry bound. The span's
        trace id rides in the skeleton (TRACE_KEY) so the server side
        of this call joins the same trace.

        ``on_stream`` receives every F_STREAM frame the server pushes
        before the final reply (streamed ops); the per-attempt timeout
        then bounds the INTER-FRAME gap, not the whole call. Pushed
        frames are advisory progress — on a retry the final reply is
        the authoritative result (a dedup hit replays no stream
        frames). ``req_id`` pins the wire request id (serving-router
        failover: the SAME id must ride the replay on a surviving
        replica so a later retry against the original still dedups).
        ``max_retries`` overrides the client-wide bound per call
        (health probes want fail-fast pings on a shared channel)."""
        op = req.get("op") if isinstance(req, dict) else None
        with _tracing.span("rpc.client", op=op or "?",
                           endpoint=self.endpoint) as sp:
            if isinstance(req, dict) and TRACE_KEY not in req:
                req = {**req, TRACE_KEY: sp.trace_id}
            t_call = time.monotonic()
            try:
                rep = self._call_inner(req, timeout, deadline,
                                       on_stream=on_stream,
                                       req_id=req_id,
                                       max_retries=max_retries)
            except Exception as e:
                _flight.record("rpc", "client_error",
                               trace_id=sp.trace_id, op=op or "?",
                               endpoint=self.endpoint,
                               error=f"{type(e).__name__}: {e}")
                raise
            dt = time.monotonic() - t_call
            _CLIENT_LATENCY.labels(op=op or "?").observe(dt)
            _flight.record("rpc", "client_call", trace_id=sp.trace_id,
                           op=op or "?", endpoint=self.endpoint,
                           seconds=round(dt, 6))
            return rep

    def _handle_error_frame(self, rep):
        """Map an F_ERROR reply to its exception. ``kind="wire"`` means
        the SERVER saw a corrupt body for our id — retryable (raise
        WireError), and crucially only for this call."""
        msg = rep.get("error", "remote error") \
            if isinstance(rep, dict) else str(rep)
        kind = rep.get("kind") if isinstance(rep, dict) else None
        if kind == "auth":
            self.stats.add("remote_errors")
            raise PSAuthError(msg)
        if kind == "wire":
            raise WireError(msg)
        self.stats.add("remote_errors")
        raise PSRemoteError(msg)

    def _call_inner(self, req, timeout, deadline, on_stream=None,
                    req_id=None, max_retries=None):
        per_attempt = timeout if timeout is not None else self.timeout
        deadline_ts = time.monotonic() + (
            deadline if deadline is not None else self.deadline)
        retry_bound = max_retries if max_retries is not None \
            else self.max_retries
        attempt = 0
        last: Exception | None = None
        frame: bytes | None = None
        self.stats.add("requests")
        while True:
            remaining = deadline_ts - time.monotonic()
            if remaining <= 0 or attempt > retry_bound:
                self.stats.add("deadline_exceeded")
                raise PSDeadlineError(
                    f"PS request to {self.endpoint} failed after "
                    f"{attempt} attempt(s): {last}") from last
            ch: _Channel | None = None
            try:
                ch = self._acquire_channel(
                    min(per_attempt, max(remaining, 0.1)),
                    exclusive=not self.mux)
                if req_id is None:
                    req_id = self._next_id()
                if frame is None:
                    frame = _build_frame(req, req_id, 0)
                waiter = ch.register(req_id)
                try:
                    t_progress = time.monotonic()
                    ch.send(frame, req_id)
                    while True:
                        gap = min(per_attempt,
                                  max(deadline_ts - time.monotonic(),
                                      0.001))
                        try:
                            kind, payload = waiter.get(timeout=gap)
                        except queue.Empty:
                            if not ch.dead \
                                    and ch.last_rx < t_progress:
                                # the whole channel is silent, not just
                                # this call: peer wedged/dead — kill it
                                # so every caller fails over/reconnects
                                ch.close()
                            raise socket.timeout(
                                f"no frame for {gap:.1f}s") from None
                        if kind == _WAITER_STREAM:
                            t_progress = time.monotonic()
                            if on_stream is not None:
                                on_stream(payload)
                            continue
                        if kind == _WAITER_REPLY:
                            return payload
                        if kind == _WAITER_ERRFRAME:
                            self._handle_error_frame(payload)
                        if kind == _WAITER_WIRE:
                            raise WireError(payload)
                        if kind == _WAITER_DEAD:
                            raise payload if isinstance(
                                payload, Exception) \
                                else ConnectionError(str(payload))
                finally:
                    ch.deregister(req_id)
            except (PSAuthError, PSRemoteError):
                raise
            except WireError as e:
                last = e
                self.stats.add("corrupt_frames")
            except socket.timeout as e:
                last = e
                self.stats.add("timeouts")
            except (ConnectionError, OSError) as e:
                last = e
            finally:
                if ch is not None:
                    self._release_channel(ch)
            self.stats.add("retries")
            attempt += 1
            pause = min(self.backoff * (2 ** (attempt - 1)),
                        self.backoff_max)
            time.sleep(pause * (0.5 + random.random()))

    def call_stream(self, req, req_id: int | None = None,
                    timeout: float | None = None,
                    stream_timeout: float | None = None):
        """Single-attempt streaming call: a GENERATOR yielding each
        F_STREAM frame the server pushes, returning the final reply as
        its StopIteration value. No internal retry — the caller owns
        failover (the serving router replays on a different replica
        with the SAME ``req_id`` so dedup still holds; docs/SERVING.md).

        ``timeout`` bounds the wait for the FIRST frame (queueing +
        prefill happen before any token); ``stream_timeout`` bounds
        every later INTER-FRAME gap — a replica wedged mid-generation
        surfaces as socket.timeout here, which is the router's
        mid-stream stall signal. Transport errors propagate raw.

        Under multiplexing many streams (and calls) share the channel;
        abandoning the generator sends F_CANCEL for this id, which the
        server turns into GeneratorExit inside its dispatch generator —
        the CONNECTION survives. In legacy mode (mux=False) the stream
        still owns its channel exclusively for its lifetime."""
        op = req.get("op") if isinstance(req, dict) else None
        first_t = timeout if timeout is not None else self.timeout
        gap_t = stream_timeout if stream_timeout is not None else first_t
        with _tracing.span("rpc.client_stream", op=op or "?",
                           endpoint=self.endpoint) as sp:
            if isinstance(req, dict) and TRACE_KEY not in req:
                req = {**req, TRACE_KEY: sp.trace_id}
            self.stats.add("requests")
            rid = req_id if req_id is not None else self._next_id()
            ch = self._acquire_channel(first_t,
                                       exclusive=not self.mux)
            done = False
            try:
                waiter = ch.register(rid)
                try:
                    t_progress = time.monotonic()
                    ch.send(_build_frame(req, rid, 0), rid)
                    cur_t = first_t
                    while True:
                        try:
                            kind, payload = waiter.get(timeout=cur_t)
                        except queue.Empty:
                            self.stats.add("timeouts")
                            if not ch.dead \
                                    and ch.last_rx < t_progress:
                                ch.close()
                            raise socket.timeout(
                                f"stream stalled ({cur_t:.1f}s)") \
                                from None
                        t_progress = time.monotonic()
                        if kind == _WAITER_STREAM:
                            cur_t = gap_t
                            yield payload
                            continue
                        if kind == _WAITER_REPLY:
                            done = True
                            return payload
                        if kind == _WAITER_ERRFRAME:
                            self._handle_error_frame(payload)
                        if kind == _WAITER_WIRE:
                            self.stats.add("corrupt_frames")
                            raise WireError(payload)
                        if kind == _WAITER_DEAD:
                            raise payload if isinstance(
                                payload, Exception) \
                                else ConnectionError(str(payload))
                finally:
                    ch.deregister(rid)
                    if not done and not ch.dead:
                        # abandoned or failed mid-stream: tell the
                        # server to cancel whatever feeds this id; the
                        # shared channel itself stays healthy
                        with contextlib.suppress(Exception):
                            ch.send(_build_frame({}, rid, F_CANCEL),
                                    rid)
            finally:
                self._release_channel(ch)


# ---------------------------------------------------------------------------
# server-side connection loop: handshake + dedup + error replies
# ---------------------------------------------------------------------------

_FRESH = object()


_NULL_SCOPE = contextlib.nullcontext()


def _reply_nbytes(obj) -> int:
    """Rough retained size of a cached reply (arrays dominate)."""
    if isinstance(obj, np.ndarray):
        return obj.nbytes + 64
    if isinstance(obj, dict):
        return 64 + sum(_reply_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return 64 + sum(_reply_nbytes(v) for v in obj)
    return 64


class DedupCache:
    """Request-id -> reply memo for mutating ops (exactly-once across
    client retries). `begin` parks duplicate ids that race an in-flight
    original; entries are evicted FIFO past `capacity` entries or
    `max_bytes` of retained reply payload (the heter dense tier caches
    gradient-bundle replies — an entry-count bound alone would retain
    gigabytes)."""

    def __init__(self, capacity: int = 65536,
                 max_bytes: int = 256 * (1 << 20)):
        self.capacity = capacity
        self.max_bytes = max_bytes
        self._cond = threading.Condition()
        self._done: dict[int, object] = {}
        self._order: list[int] = []
        self._bytes = 0
        # newest committed req_id per client token (req_id >> 32): a
        # client serializes its calls, so only its LATEST request can
        # be mid-retry — protecting that one entry per client from
        # eviction closes the evicted-while-retrying double-apply
        # window at O(#clients) extra retention. The token set itself
        # is FIFO-bounded (first-seen order) so weeks of client churn
        # cannot pin unbounded replies; an expelled token's entry just
        # becomes normally evictable again.
        self._newest: dict[int, int] = {}
        self._token_order: list[int] = []
        self.token_capacity = 4096
        self._inflight: set[int] = set()

    def begin(self, req_id: int):
        """Returns the cached reply for a duplicate, or _FRESH (and
        marks the id in-flight) for a first arrival."""
        with self._cond:
            while True:
                if req_id in self._done:
                    return self._done[req_id]
                if req_id not in self._inflight:
                    self._inflight.add(req_id)
                    return _FRESH
                if not self._cond.wait(timeout=600):
                    raise TimeoutError(
                        f"duplicate request {req_id:#x} stuck behind an "
                        f"in-flight original")

    def commit(self, req_id: int, reply):
        with self._cond:
            self._inflight.discard(req_id)
            if req_id not in self._done:
                self._done[req_id] = reply
                self._order.append(req_id)
                self._bytes += _reply_nbytes(reply)
                token = req_id >> 32
                if token not in self._newest:
                    self._token_order.append(token)
                    while len(self._token_order) > self.token_capacity:
                        self._newest.pop(self._token_order.pop(0),
                                         None)
                self._newest[token] = req_id
                # evict FIFO past the entry/byte bound, but never a
                # client's newest entry — that one may be mid-retry
                scanned = 0
                while scanned < len(self._order) and \
                        (len(self._order) > self.capacity
                         or self._bytes > self.max_bytes):
                    old = self._order.pop(0)
                    if self._newest.get(old >> 32) == old:
                        self._order.append(old)  # protected; keep
                        scanned += 1
                        continue
                    gone = self._done.pop(old, None)
                    if gone is not None:
                        self._bytes -= _reply_nbytes(gone)
            self._cond.notify_all()

    def abort(self, req_id: int):
        with self._cond:
            self._inflight.discard(req_id)
            self._cond.notify_all()

    # -- snapshot support ----------------------------------------------
    def export(self) -> tuple[np.ndarray, list[bytes]]:
        with self._cond:
            ids = np.array(self._order, np.uint64)
            blobs = [encode_body(self._done[i]) for i in self._order]
        return ids, blobs

    def import_(self, ids: np.ndarray, blobs: list[bytes]):
        with self._cond:
            self._done.clear()
            self._order = []
            self._bytes = 0
            self._newest = {}
            self._token_order = []
            for i, blob in zip(ids.tolist(), blobs):
                reply = decode_body(blob)
                self._done[int(i)] = reply
                self._order.append(int(i))
                self._bytes += _reply_nbytes(reply)
                if (int(i) >> 32) not in self._newest:
                    self._token_order.append(int(i) >> 32)
                self._newest[int(i) >> 32] = int(i)
            self._cond.notify_all()


class RpcServerState:
    """Per-server transport state shared by all connection handlers."""

    def __init__(self, read_ops=frozenset(), secret: str | None = None,
                 dedup_capacity: int = 65536, after_commit=None,
                 commit_scope=None, after_retry=None,
                 expose_req_id: bool = False, before_reply=None):
        self.read_ops = frozenset(read_ops)
        # inject the wire request id into the skeleton as "_req_id"
        # before dispatch: the serving router pins its DOWNSTREAM call
        # ids to the upstream id so a failover replay carries the same
        # identity on whichever replica serves it
        self.expose_req_id = bool(expose_req_id)
        self.secret = secret if secret is not None \
            else os.environ.get("PADDLE_PS_SECRET")
        self.dedup = DedupCache(dedup_capacity)
        # called with the op name when a MUTATING request is answered
        # from the dedup cache (client retry): the original dispatch may
        # have died between commit and its after_commit side effect, so
        # this is the hook's chance to finish pending persistence. It
        # must be idempotent and must NOT count a new mutation.
        self.after_retry = after_retry
        # called with the op name after a mutating op was dispatched and
        # its dedup entry recorded, BEFORE the reply is sent — the
        # snapshot hook runs here so a post-snapshot crash still yields
        # exactly-once on retry
        self.after_commit = after_commit
        # optional op -> lock/context-manager hook: when set, dispatch
        # + dedup.commit + after_commit run inside it, so a concurrent
        # snapshot export can never observe an applied mutation whose
        # dedup id is missing (or vice versa). Only ops whose dispatch
        # cannot block should return a scope — a barrier op waiting on
        # straggler trainers inside a shared lock would stall the shard
        self.commit_scope = commit_scope
        # optional (op, req, req_id, reply) hook called INSIDE the
        # commit scope right after dedup.commit — the WAL tier journals
        # the mutation (touched rows + request id) here, so a record is
        # on disk before the reply leaves and replay order matches
        # apply order
        self.journal = None
        # optional (op, req_id) hook called for mutating ops after
        # after_commit but BEFORE the reply frame is enqueued, OUTSIDE
        # the commit scope (it may block without serializing other
        # pushes) — the PS HA semi-sync ack gate waits here until K
        # standbys hold the journaled record (or degrades to async)
        self.before_reply = before_reply


class _ServerConn:
    """One accepted connection's mux state: a writer thread serializes
    outgoing frames (replies and stream pushes from many concurrent
    handlers interleave on the wire), a bounded per-connection executor
    runs the handlers, and a cancel event per in-flight id lets
    F_CANCEL (or connection death) stop a dispatch generator."""

    def __init__(self, sock: socket.socket, dispatch,
                 state: RpcServerState):
        self.sock = sock
        self.dispatch = dispatch
        self.state = state
        self.dead = False
        self.max_workers = max(1, int(_env_float(
            "PADDLE_TPU_RPC_SERVER_INFLIGHT", 32)))
        self._sendq: queue.Queue = queue.Queue(maxsize=256)
        self._clock = threading.Lock()
        self._cancels: dict[int, threading.Event] = {}
        self._sem = threading.BoundedSemaphore(self.max_workers)
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_workers,
            thread_name_prefix="rpc-srv")
        self._writer_thread = threading.Thread(
            target=self._writer, daemon=True, name="rpc-srv-w")
        self._writer_thread.start()

    # -- outgoing -------------------------------------------------------
    def enqueue(self, obj, req_id: int, flags: int = 0):
        frame = _build_frame(obj, req_id, flags)
        while True:
            if self.dead:
                raise ConnectionError("connection writer is down")
            try:
                self._sendq.put((frame, req_id), timeout=1.0)
                return
            except queue.Full:
                continue

    def _requeue(self, frame: bytes, req_id: int):
        if not self.dead:
            with contextlib.suppress(queue.Full):
                self._sendq.put((frame, req_id), timeout=1.0)

    def _writer(self):
        while True:
            item = self._sendq.get()
            if item is None:
                return
            frame, rid = item
            try:
                _send_mux(self.sock, frame, "server", rid,
                          self._requeue)
            except Exception:
                self._fatal()
                return

    # -- incoming -------------------------------------------------------
    def run(self):
        hdr = bytearray(HEADER_SIZE)
        try:
            while True:
                try:
                    req, rid, flags, _n = _read_frame_mux(
                        self.sock, _BUFFER_POOL, hdr)
                except _FrameError as fe:
                    # corrupt body on an intact frame: answer THAT id
                    # with a retryable wire error; every other call on
                    # this socket is untouched
                    _MUX_FRAME_ERRORS.labels(side="server").inc()
                    if not fe.flags & F_CANCEL:
                        self.enqueue(
                            {"error": f"WireError: {fe}",
                             "kind": "wire"}, fe.req_id, F_ERROR)
                    continue
                if flags & F_CANCEL:
                    with self._clock:
                        ev = self._cancels.get(rid)
                    if ev is not None:
                        ev.set()
                    continue
                # re-read the injector each request: a chaos drill that
                # (re)arms the knobs mid-run must hit connections that
                # were already open (the writer reads it per frame too)
                inj = injector()
                armed = inj.count_request() if inj.active else False
                if inj.active:
                    inj.maybe_kill("recv", armed)
                cancel_ev = threading.Event()
                with self._clock:
                    self._cancels[rid] = cancel_ev
                self._sem.acquire()
                try:
                    self._pool.submit(self._handle, req, rid,
                                      cancel_ev, armed)
                except BaseException:
                    self._sem.release()
                    raise
        except (PSAuthError, WireError, ConnectionError, OSError):
            pass
        finally:
            self._shutdown()

    # -- handler --------------------------------------------------------
    def _drain(self, gen, req_id: int, cancel_ev: threading.Event):
        """Send every yielded object as an F_STREAM frame; the return
        value is the final reply. F_CANCEL (or connection death)
        surfaces between frames as _Cancelled — gen.close() raises
        GeneratorExit at the dispatch generator's yield point so it can
        cancel whatever produced the stream."""
        try:
            while True:
                try:
                    item = next(gen)
                except StopIteration as stop:
                    return stop.value if stop.value is not None else {}
                if cancel_ev.is_set():
                    raise _Cancelled()
                self.enqueue(item, req_id, F_STREAM)
        finally:
            gen.close()

    def _handle(self, req, req_id: int, cancel_ev: threading.Event,
                armed: bool):
        state = self.state
        try:
            op = req.get("op") if isinstance(req, dict) else None
            # wire-carried trace id (TRACE_KEY in the skeleton):
            # stripped before dispatch, re-rooted as this side's span
            # context so handler-side spans join the caller's trace
            wire_tid = req.pop(TRACE_KEY, None) \
                if isinstance(req, dict) else None
            if state.expose_req_id and isinstance(req, dict):
                req["_req_id"] = req_id
            _SERVER_REQS.labels(op=op or "?").inc()
            _flight.record("rpc", "server_request",
                           trace_id=wire_tid, op=op or "?",
                           req_id=req_id)
            inj = injector()
            mutating = op not in state.read_ops
            if mutating and req_id:
                cached = state.dedup.begin(req_id)
                if cached is not _FRESH:
                    _SERVER_DEDUP_HITS.labels(op=op or "?").inc()
                    if state.after_retry is not None:
                        state.after_retry(op)
                    if inj.active:
                        inj.maybe_kill("reply", armed)
                    self.enqueue(cached, req_id)
                    return
            scope = state.commit_scope(op) \
                if state.commit_scope is not None else None
            err = None
            with scope if scope is not None else _NULL_SCOPE:
                try:
                    with _tracing.span(f"rpc.server.{op or 'raw'}",
                                       trace_id=wire_tid,
                                       op=op or "?"):
                        rep = self.dispatch(req)
                        if isinstance(rep, types.GeneratorType):
                            rep = self._drain(rep, req_id, cancel_ev)
                except _Cancelled:
                    # the client abandoned this id: no reply to send,
                    # nothing to memoise — the op did not complete
                    if mutating and req_id:
                        state.dedup.abort(req_id)
                    return
                except Exception as e:
                    # application/dispatch failure (including barrier
                    # timeouts): report as an error frame instead of
                    # silently killing the connection
                    if mutating and req_id:
                        state.dedup.abort(req_id)
                    err = {"error": f"{type(e).__name__}: {e}",
                           "kind": "app"}
                else:
                    if mutating and req_id:
                        state.dedup.commit(req_id, rep)
                        if state.journal is not None:
                            # WAL write-ahead: rows + request id land
                            # on disk inside the commit scope, so a
                            # crash-restore replays this mutation AND
                            # dedups its retry (exactly-once survives)
                            state.journal(op, req, req_id, rep)
            if err is not None:
                _SERVER_ERRORS.labels(op=op or "?").inc()
                _flight.record("rpc", "server_error",
                               trace_id=wire_tid, op=op or "?",
                               error=err.get("error"))
                self.enqueue(err, req_id, F_ERROR)
                return
            if mutating and state.after_commit is not None:
                # outside the commit scope (a snapshot's disk write
                # must not stall other pushes on the commit lock) but
                # before the reply: a crash in here still resolves to
                # exactly-once — the mutation IS committed, so the
                # client's retry lands on the dedup cache. Failures
                # (e.g. snapshot disk error) propagate and end the
                # connection for the same reason.
                state.after_commit(op)
            if mutating and req_id and state.before_reply is not None:
                state.before_reply(op, req_id)
            if inj.active:
                inj.maybe_kill("reply", armed)
            self.enqueue(rep, req_id)
        except Exception:
            # writer down / encode failure: the connection is beyond
            # per-request recovery
            self._fatal()
        finally:
            with self._clock:
                self._cancels.pop(req_id, None)
            self._sem.release()

    # -- teardown -------------------------------------------------------
    def _fatal(self):
        self.dead = True
        # _hard_close: run() is blocked in recv on this socket — a bare
        # close would strand it (and the client) until their timeouts
        _hard_close(self.sock)

    def _shutdown(self):
        self.dead = True
        with self._clock:
            cancels = list(self._cancels.values())
        for ev in cancels:
            # connection death cancels every in-flight stream: their
            # next frame can never be delivered
            ev.set()
        self._sendq.put(None)
        self._pool.shutdown(wait=False)
        _hard_close(self.sock)


def serve_connection(sock: socket.socket, dispatch, state: RpcServerState):
    """One connection's multiplexed request loop. Requests are handled
    concurrently (bounded by PADDLE_TPU_RPC_SERVER_INFLIGHT) and their
    replies/stream frames interleave on the wire, each tagged with its
    request id. Application errors become error frames; body-local
    corruption poisons only its own request id; transport errors end
    the connection (the client's retry path owns recovery). A dispatch
    that returns a GENERATOR streams: yielded objects go out as
    F_STREAM frames, the generator's return value is the final
    (dedup-memoised) reply; an F_CANCEL from the client raises
    GeneratorExit into the generator."""
    try:
        # server-push streams (replication feeds, invalidations,
        # pub_watch) are one-directional: without NODELAY, Nagle holds
        # each small frame for the peer's delayed ACK (~40ms/record)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    try:
        server_handshake(sock, state.secret)
    except (PSAuthError, WireError, ConnectionError, OSError):
        _hard_close(sock)
        return
    _ServerConn(sock, dispatch, state).run()
