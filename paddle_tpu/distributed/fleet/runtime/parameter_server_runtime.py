"""Parameter-server runtime (reference distributed/fleet/runtime/parameter_server_runtime.py).

TPU-native PS tier: a host-resident sharded KV store served over DCN for the
sparse-embedding workload (PaddleRec configs). The dense path should instead
use mesh-sharded embeddings + all_to_all (paddle_tpu.parallel.embedding).
Round-1 scope: single-host in-process KV; the RPC transport lands with the
C++ runtime batch.
"""
from __future__ import annotations

import numpy as np

__all__ = ["ParameterServerRuntime", "LargeScaleKV"]


class LargeScaleKV:
    """In-memory sharded sparse table (reference operators/distributed/large_scale_kv.h)."""

    def __init__(self, dim: int, init_std: float = 0.01, shards: int = 8):
        self.dim = dim
        self.init_std = init_std
        self.shards = [dict() for _ in range(shards)]

    def _shard(self, key: int) -> dict:
        return self.shards[key % len(self.shards)]

    def pull(self, keys: np.ndarray) -> np.ndarray:
        out = np.empty((len(keys), self.dim), dtype=np.float32)
        for i, k in enumerate(keys.tolist()):
            s = self._shard(k)
            row = s.get(k)
            if row is None:
                row = np.random.normal(
                    0, self.init_std, self.dim).astype(np.float32)
                s[k] = row
            out[i] = row
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray, lr: float = 1.0):
        for k, g in zip(keys.tolist(), grads):
            s = self._shard(k)
            row = s.get(k)
            if row is None:
                row = np.random.normal(
                    0, self.init_std, self.dim).astype(np.float32)
            s[k] = row - lr * g

    def size(self) -> int:
        return sum(len(s) for s in self.shards)

    def save(self, path: str):
        import pickle
        with open(path, "wb") as f:
            pickle.dump(self.shards, f, protocol=4)

    def load(self, path: str):
        import pickle
        with open(path, "rb") as f:
            self.shards = pickle.load(f)


class ParameterServerRuntime:
    def __init__(self, role_maker):
        self._role_maker = role_maker
        self._tables: dict[str, LargeScaleKV] = {}

    def init_server(self, *args):
        pass

    def run_server(self):
        pass

    def init_worker(self):
        pass

    def stop_worker(self):
        pass

    def get_table(self, name: str, dim: int) -> LargeScaleKV:
        if name not in self._tables:
            self._tables[name] = LargeScaleKV(dim)
        return self._tables[name]
