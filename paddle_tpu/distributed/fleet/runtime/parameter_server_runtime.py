"""Parameter-server runtime — host-resident sharded KV over TCP.

Reference chain this replaces: `listen_and_serv` event loop
(operators/distributed_ops/listen_and_serv_op.cc:352), gRPC/BRPC transport
(operators/distributed/grpc/), `large_scale_kv.h` in-memory sparse table,
and the fleet runtime glue (distributed/fleet/runtime/
parameter_server_runtime.py).  TPU stance (SURVEY §7): embedding tables
that FIT in HBM should use the mesh-sharded design in
paddle_tpu.parallel.embedding; this host tier serves the beyond-HBM
PaddleRec configs, with key-hash sharding across servers and a
pickle-over-TCP protocol (one request per pull/push batch — the
Communicator's merge semantics come from batched numpy application).
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading

import numpy as np

__all__ = ["ParameterServerRuntime", "LargeScaleKV", "PSServer", "PSClient"]


class LargeScaleKV:
    """In-memory sparse table (reference large_scale_kv.h).

    Hot path: the C++ open-addressing core in paddle_tpu/native/kv_store.cc
    (id->slot hash + contiguous row arena, no Python per row). Falls back
    to the vectorized numpy implementation when no toolchain is available
    or PADDLE_TPU_DISABLE_NATIVE is set."""

    def __init__(self, dim: int, init_std: float = 0.01, seed: int = 0):
        self.dim = dim
        self.init_std = init_std
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        self._index: dict[int, int] = {}
        self._data = np.empty((0, dim), np.float32)
        self._lock = threading.Lock()
        self._native = None
        import os
        if not os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
            from ....native import available, NativeKV
            if available():
                self._native = NativeKV(dim, init_std, seed)

    def _ensure(self, keys: np.ndarray) -> np.ndarray:
        """Slots for keys, creating missing rows in one batched init."""
        idx = self._index
        # dedup while preserving order: duplicate new keys in one batch
        # must allocate ONE slot (else start drifts off the data high-water
        # mark and later inserts clobber existing rows)
        missing = list(dict.fromkeys(
            k for k in keys.tolist() if k not in idx))
        if missing:
            start = len(idx)
            fresh = self._rng.normal(
                0, self.init_std,
                (len(missing), self.dim)).astype(np.float32)
            need = start + len(missing)
            if need > len(self._data):
                grow = np.empty((max(need, 2 * len(self._data) + 64),
                                 self.dim), np.float32)
                grow[:len(self._data)] = self._data
                self._data = grow
            self._data[start:start + len(missing)] = fresh
            for i, k in enumerate(missing):
                idx[k] = start + i
        return np.fromiter((idx[k] for k in keys.tolist()), np.int64,
                           len(keys))

    def pull(self, keys: np.ndarray) -> np.ndarray:
        with self._lock:
            if self._native is not None:
                return self._native.pull(keys)
            slots = self._ensure(np.asarray(keys).ravel())
            return self._data[slots].copy()

    def push(self, keys: np.ndarray, grads: np.ndarray, lr: float = 1.0):
        """SGD apply (reference async PS applies grads on arrival);
        duplicate keys accumulate."""
        with self._lock:
            if self._native is not None:
                self._native.push(keys, grads, lr)
                return
            slots = self._ensure(np.asarray(keys).ravel())
            np.add.at(self._data, slots,
                      (-lr * np.asarray(grads)).astype(np.float32))

    def size(self) -> int:
        with self._lock:
            if self._native is not None:
                return self._native.size()
            return len(self._index)

    def save(self, path: str):
        with self._lock:
            if self._native is not None:
                keys, rows = self._native.export()
            else:
                keys = np.fromiter(self._index, np.int64,
                                   len(self._index))
                slots = np.fromiter(self._index.values(), np.int64,
                                    len(self._index))
                rows = self._data[slots]
            with open(path, "wb") as f:
                pickle.dump({"dim": self.dim, "keys": keys,
                             "rows": rows}, f, protocol=4)

    def load(self, path: str):
        with open(path, "rb") as f:
            blob = pickle.load(f)
        with self._lock:
            self.dim = blob["dim"]
            if self._native is not None:
                from ....native import NativeKV
                # keep the instance seed so fresh rows created after a
                # restore stay reproducible
                self._native = NativeKV(self.dim, self.init_std,
                                        self.seed)
                if len(blob["keys"]):
                    self._native.import_(blob["keys"], blob["rows"])
                return
            self._data = np.ascontiguousarray(blob["rows"])
            self._index = {int(k): i for i, k in enumerate(blob["keys"])}


# ---------------------------------------------------------------------------
# transport: length-prefixed pickle over TCP
# ---------------------------------------------------------------------------

def _send_msg(sock, obj):
    blob = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<Q", len(blob)) + blob)
    return 8 + len(blob)


def _recv_msg_sized(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    n = struct.unpack("<Q", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf)), 8 + n


def _recv_msg(sock):
    return _recv_msg_sized(sock)[0]


class _SyncRound:
    """Sync-mode round state for one PS shard (reference
    RunSyncLoop + send_barrier/fetch_barrier rounds,
    operators/distributed/communicator.h:253 HalfAsync barrier logic):
    push_sync only BUFFERS gradients; the last trainer through the send
    barrier applies the whole round (mean over trainers) before anyone is
    released; the fetch barrier then holds the next round's apply until
    every trainer pulled the fresh values."""

    def __init__(self, trainers: int):
        self.trainers = trainers
        self.cond = threading.Condition()
        self.pending: list[tuple] = []
        self.send_done: set[int] = set()
        self.fetch_done: set[int] = set()
        self.round = 0
        self.fround = 0

    def push(self, item):
        with self.cond:
            self.pending.append(item)

    def send_barrier(self, worker: int, apply_fn) -> int:
        with self.cond:
            self.send_done.add(int(worker))
            if len(self.send_done) >= self.trainers:
                pending, self.pending = self.pending, []
                apply_fn(pending)
                self.send_done.clear()
                self.round += 1
                self.cond.notify_all()
                return self.round
            r = self.round
            if not self.cond.wait_for(lambda: self.round > r, timeout=300):
                raise TimeoutError("send_barrier: trainers missing")
            return self.round

    def fetch_barrier(self, worker: int) -> int:
        with self.cond:
            self.fetch_done.add(int(worker))
            if len(self.fetch_done) >= self.trainers:
                self.fetch_done.clear()
                self.fround += 1
                self.cond.notify_all()
                return self.fround
            fr = self.fround
            if not self.cond.wait_for(lambda: self.fround > fr,
                                      timeout=300):
                raise TimeoutError("fetch_barrier: trainers missing")
            return self.fround


class _DGCRound:
    """One sparse-gradient exchange round (DGC transport): trainers push
    their top-k (idx, val) pairs; once every trainer has pushed, pulls
    return the MERGED sparse gradient (duplicate indices summed,
    vectorized at seal time). The round recycles when every trainer has
    pulled — lockstep rounds like the reference's sparse allreduce.
    Stragglers raise TimeoutError (matching _SyncRound) instead of
    hanging the handler thread."""

    def __init__(self, trainers: int):
        self.trainers = trainers
        self.cond = threading.Condition()
        self._reset()

    def _reset(self):
        self.parts: list = []
        self.pushed: set[int] = set()
        self.pulled: set[int] = set()
        self.merged = None

    def push(self, worker: int, idx, val):
        with self.cond:
            if not self.cond.wait_for(
                    lambda: worker not in self.pushed, timeout=300):
                raise TimeoutError(
                    "dgc round not drained — a trainer never pulled")
            self.parts.append((np.asarray(idx, np.int64).ravel(),
                               np.asarray(val, np.float32).ravel()))
            self.pushed.add(worker)
            if len(self.pushed) == self.trainers:
                allidx = np.concatenate([p[0] for p in self.parts])
                allval = np.concatenate([p[1] for p in self.parts])
                uniq, inv = np.unique(allidx, return_inverse=True)
                summed = np.bincount(inv, weights=allval,
                                     minlength=len(uniq))
                self.merged = (uniq, summed.astype(np.float32))
                self.cond.notify_all()
            return True

    def pull(self, worker: int):
        with self.cond:
            if not self.cond.wait_for(lambda: self.merged is not None,
                                      timeout=300):
                raise TimeoutError(
                    "dgc round incomplete — trainers missing: "
                    f"{sorted(set(range(self.trainers)) - self.pushed)}")
            idx, val = self.merged
            self.pulled.add(worker)
            if len(self.pulled) == self.trainers:
                self._reset()
                self.cond.notify_all()
            return {"idx": idx, "val": val}


class PSServer(socketserver.ThreadingTCPServer):
    """One PS shard: serves pull/push/save/size for its tables (reference
    listen_and_serv_op RunAsyncLoop — apply-on-arrival, no global
    barrier; RunSyncLoop when the sync ops are used). Port 0 binds an
    ephemeral port; `endpoint` reports it."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, endpoint: str, worker_timeout: float = 60.0):
        host, port = endpoint.rsplit(":", 1)
        self.tables: dict[str, LargeScaleKV] = {}
        self._tables_lock = threading.Lock()
        self._sync: _SyncRound | None = None
        self._sync_lock = threading.Lock()
        # worker liveness (reference operators/distributed/
        # heart_beat_monitor.h:54): last-seen stamp per worker id;
        # lost_workers() reports ids silent past the timeout
        self.worker_timeout = worker_timeout
        self._beats: dict[int, float] = {}
        self._dgc: dict[str, _DGCRound] = {}
        self._beats_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        req = _recv_msg(self.request)
                        _send_msg(self.request, outer._dispatch(req))
                except (ConnectionError, OSError):
                    pass

        super().__init__((host, int(port)), Handler)
        self.endpoint = f"{host}:{self.server_address[1]}"

    def table(self, name: str, dim: int,
              init_std: float = 0.01) -> LargeScaleKV:
        with self._tables_lock:
            if name not in self.tables:
                self.tables[name] = LargeScaleKV(dim, init_std=init_std)
            return self.tables[name]

    def _dispatch(self, req: dict):
        op = req["op"]
        if op == "pull":
            return self.table(req["table"], req["dim"],
                              req.get("init_std", 0.01)).pull(req["keys"])
        if op == "push":
            self.table(req["table"], req["dim"],
                       req.get("init_std", 0.01)).push(
                req["keys"], req["grads"], req.get("lr", 1.0))
            return True
        if op == "save":
            tag = self.endpoint.replace(":", "_")
            with self._tables_lock:
                items = list(self.tables.items())
            for name, t in items:
                t.save(f"{req['dirname']}/{name}.{tag}.kv")
            return True
        if op == "size":
            t = self.tables.get(req["table"])
            return 0 if t is None else t.size()
        if op == "push_sync":
            self._sync_state(req["trainers"]).push(
                (req["table"], req["dim"], req["keys"], req["grads"],
                 req.get("lr", 1.0)))
            return True
        if op == "send_barrier":
            def apply_fn(pending):
                n = max(int(req["trainers"]), 1)
                for table, dim, keys, grads, lr in pending:
                    # mean over trainers: matches the single-process
                    # full-batch step when each trainer computes the mean
                    # loss of its batch shard
                    self.table(table, dim).push(keys, grads, lr / n)
            return self._sync_state(req["trainers"]).send_barrier(
                req["worker"], apply_fn)
        if op == "fetch_barrier":
            return self._sync_state(req["trainers"]).fetch_barrier(
                req["worker"])
        if op == "ping":
            return "pong"
        if op == "heartbeat":
            import time
            with self._beats_lock:
                self._beats[int(req["worker"])] = time.time()
            return True
        if op == "lost_workers":
            return self.lost_workers()
        if op == "dgc_push":
            # sparse gradient round (DGC transport, reference dgc_op.h +
            # sparse allreduce in operators/collective): accumulate each
            # trainer's top-k (idx, val) pairs; seal when all arrived.
            # Timeouts surface as an error PAYLOAD — TimeoutError is an
            # OSError subclass the connection handler would swallow
            try:
                return self._dgc_round(req["table"], int(req["trainers"])
                                       ).push(int(req["worker"]),
                                              req["idx"], req["val"])
            except (TimeoutError, RuntimeError) as e:
                return {"error": str(e)}
        if op == "dgc_pull":
            try:
                return self._dgc_round(req["table"], int(req["trainers"])
                                       ).pull(int(req["worker"]))
            except (TimeoutError, RuntimeError) as e:
                return {"error": str(e)}
        raise ValueError(f"unknown PS op {op!r}")

    def _dgc_round(self, table: str, trainers: int) -> "_DGCRound":
        with self._sync_lock:
            r = self._dgc.get(table)
            if r is None:
                r = self._dgc[table] = _DGCRound(trainers)
            elif r.trainers != trainers:
                if r.pushed or r.pulled:
                    raise RuntimeError(
                        f"dgc trainer count changed mid-round on "
                        f"{table!r} ({r.trainers} -> {trainers})")
                r = self._dgc[table] = _DGCRound(trainers)
            return r

    def _sync_state(self, trainers: int) -> _SyncRound:
        with self._sync_lock:
            if self._sync is None:
                self._sync = _SyncRound(int(trainers))
            elif self._sync.trainers != int(trainers):
                st = self._sync
                with st.cond:
                    idle = not st.pending and not st.send_done and \
                        not st.fetch_done
                if not idle:
                    raise ValueError(
                        f"sync trainer count changed mid-round "
                        f"({st.trainers} -> {trainers}) with buffered "
                        f"state — restart the job cleanly")
                # a new job with a different world size: fresh round state
                self._sync = _SyncRound(int(trainers))
            return self._sync

    def lost_workers(self) -> list[int]:
        import time
        now = time.time()
        with self._beats_lock:  # handler threads insert concurrently
            beats = list(self._beats.items())
        return sorted(w for w, t in beats
                      if now - t > self.worker_timeout)

    def serve_in_thread(self) -> threading.Thread:
        th = threading.Thread(target=self.serve_forever, daemon=True)
        th.start()
        return th


class PSClient:
    """Worker-side stub: key-hash routing across server shards (reference
    ps_dispatcher hash dispatch + Communicator send path)."""

    def __init__(self, endpoints: list[str]):
        self.endpoints = list(endpoints)
        self._socks: list[socket.socket | None] = [None] * len(endpoints)
        self._locks = [threading.Lock() for _ in endpoints]
        self._pool = None  # lazy persistent fan-out pool
        # wire accounting (bench/diagnostics): bytes on the TCP
        # transport; own lock — _call runs concurrently from the
        # per-endpoint fan-out threads
        self.bytes_out = 0
        self.bytes_in = 0
        self._bytes_lock = threading.Lock()

    def _sock(self, i: int) -> socket.socket:
        if self._socks[i] is None:
            import time
            host, port = self.endpoints[i].rsplit(":", 1)
            # retry the connect: workers routinely start before their
            # server finished binding (reference brpc channel retries)
            last = None
            for attempt in range(30):
                try:
                    # generous timeout: sync-mode barrier calls block
                    # server-side until the whole trainer round arrives
                    s = socket.create_connection((host, int(port)),
                                                 timeout=330)
                    break
                except OSError as e:
                    last = e
                    time.sleep(min(0.2 * (attempt + 1), 2.0))
            else:
                raise ConnectionError(
                    f"PS server {self.endpoints[i]} unreachable: {last}")
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[i] = s
        return self._socks[i]

    def _call(self, i: int, req: dict):
        with self._locks[i]:
            s = self._sock(i)
            n_out = _send_msg(s, req)
            obj, n_in = _recv_msg_sized(s)
        with self._bytes_lock:
            self.bytes_out += n_out
            self.bytes_in += n_in
        return obj

    def _route(self, keys: np.ndarray) -> np.ndarray:
        return (keys.astype(np.int64) % len(self.endpoints)).astype(np.int64)

    def _fanout(self, calls):
        """Dispatch shard RPCs concurrently over a persistent pool
        (reference Communicator's long-lived send threads); sequential
        round-trips would make latency N_shards x RTT."""
        if len(calls) <= 1:
            return [fn() for fn in calls]
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=len(self.endpoints),
                thread_name_prefix="ps-client")
        return list(self._pool.map(lambda fn: fn(), calls))

    def pull(self, table: str, dim: int, keys,
             init_std: float = 0.01) -> np.ndarray:
        keys = np.asarray(keys, np.int64).ravel()
        owner = self._route(keys)
        out = np.empty((len(keys), dim), np.float32)
        masks = [(i, owner == i) for i in range(len(self.endpoints))]
        masks = [(i, m) for i, m in masks if m.any()]
        res = self._fanout([
            (lambda i=i, m=m: self._call(i, {"op": "pull", "table": table,
                                             "dim": dim,
                                             "keys": keys[m],
                                             "init_std": init_std}))
            for i, m in masks])
        for (i, m), r in zip(masks, res):
            out[m] = r
        return out

    def push(self, table: str, dim: int, keys, grads, lr: float = 1.0,
             sync: bool = False, trainers: int = 1,
             init_std: float = 0.01):
        keys = np.asarray(keys, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(keys), dim)
        owner = self._route(keys)
        op = "push_sync" if sync else "push"
        masks = [(i, owner == i) for i in range(len(self.endpoints))]
        self._fanout([
            (lambda i=i, m=m: self._call(i, {"op": op, "table": table,
                                             "dim": dim, "keys": keys[m],
                                             "grads": grads[m],
                                             "lr": lr,
                                             "trainers": trainers,
                                             "init_std": init_std}))
            for i, m in masks if m.any()])

    def send_barrier(self, worker: int, trainers: int):
        """Block until every trainer finished this round's pushes; the
        last arrival applies the buffered round (reference
        send_barrier round semantics)."""
        self._fanout([
            (lambda i=i: self._call(i, {"op": "send_barrier",
                                        "worker": worker,
                                        "trainers": trainers}))
            for i in range(len(self.endpoints))])

    def fetch_barrier(self, worker: int, trainers: int):
        """Block until every trainer pulled the freshly applied params."""
        self._fanout([
            (lambda i=i: self._call(i, {"op": "fetch_barrier",
                                        "worker": worker,
                                        "trainers": trainers}))
            for i in range(len(self.endpoints))])

    def size(self, table: str) -> int:
        return sum(self._call(i, {"op": "size", "table": table})
                   for i in range(len(self.endpoints)))

    def heartbeat(self, worker_id: int):
        """Liveness ping to every shard (reference HeartBeatMonitor's
        worker-side UPDATE)."""
        self._fanout([
            (lambda i=i: self._call(i, {"op": "heartbeat",
                                        "worker": worker_id}))
            for i in range(len(self.endpoints))])

    def lost_workers(self) -> list[int]:
        lost: set[int] = set()
        for i in range(len(self.endpoints)):
            lost.update(self._call(i, {"op": "lost_workers"}))
        return sorted(lost)

    def save(self, dirname: str):
        for i in range(len(self.endpoints)):
            self._call(i, {"op": "save", "dirname": dirname})

    # -- DGC sparse-gradient rounds (shard by index hash) ----------------
    def dgc_allreduce(self, name: str, idx, val, worker: int,
                      trainers: int):
        """Exchange top-k sparse gradients: push this worker's (idx,
        val), receive the all-trainer merged sparse gradient. Wire cost
        is O(k) both ways vs O(N) for a dense exchange — this is the
        DGC transport the dgc_momentum op's compression exists for."""
        idx = np.asarray(idx, np.int64).ravel()
        val = np.asarray(val, np.float32).ravel()
        owner = self._route(idx)
        calls = []
        for i in range(len(self.endpoints)):
            m = owner == i
            calls.append((lambda i=i, m=m: self._call(
                i, {"op": "dgc_push", "table": name, "idx": idx[m],
                    "val": val[m], "worker": worker,
                    "trainers": trainers})))
        for r in self._fanout(calls):
            if isinstance(r, dict) and "error" in r:
                raise RuntimeError(f"dgc_push failed: {r['error']}")
        parts = self._fanout([
            (lambda i=i: self._call(i, {"op": "dgc_pull", "table": name,
                                        "worker": worker,
                                        "trainers": trainers}))
            for i in range(len(self.endpoints))])
        for p in parts:
            if "error" in p:
                raise RuntimeError(f"dgc_pull failed: {p['error']}")
        midx = np.concatenate([p["idx"] for p in parts])
        mval = np.concatenate([p["val"] for p in parts])
        order = np.argsort(midx, kind="stable")
        return midx[order], mval[order]

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        for s in self._socks:
            if s is not None:
                s.close()
        self._socks = [None] * len(self.endpoints)


class ParameterServerRuntime:
    """fleet runtime: the server role owns a PSServer shard; the worker
    role owns a PSClient over all server endpoints (reference
    runtime/parameter_server_runtime.py lifecycle)."""

    def __init__(self, role_maker):
        self._role_maker = role_maker
        self.server: PSServer | None = None
        self.client: PSClient | None = None
        self._thread: threading.Thread | None = None

    # -- server lifecycle ----------------------------------------------
    def init_server(self, *args, **kwargs):
        eps = self._role_maker.get_pserver_endpoints()
        me = eps[self._role_maker.server_index()]
        self.server = PSServer(me)
        model_dir = args[0] if args else kwargs.get("dirname")
        if model_dir:
            import glob
            import os
            tag = self.server.endpoint.replace(":", "_")
            for path in glob.glob(f"{model_dir}/*.{tag}.kv"):
                name = os.path.basename(path).split(".")[0]
                t = LargeScaleKV(1)
                t.load(path)
                self.server.tables[name] = t

    def run_server(self, block: bool = False):
        if self.server is None:
            self.init_server()
        if block:
            self.server.serve_forever()
        else:
            self._thread = self.server.serve_in_thread()
        return self.server

    def stop_server(self):
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()

    # -- worker lifecycle ----------------------------------------------
    def init_worker(self):
        self.client = PSClient(self._role_maker.get_pserver_endpoints())
        return self.client

    def stop_worker(self):
        if self.client is not None:
            self.client.close()

    def get_table(self, name: str, dim: int) -> LargeScaleKV:
        """In-process access (single-process/local mode) — no socket."""
        if self.server is not None:
            return self.server.table(name, dim)
        if not hasattr(self, "_local_tables"):
            self._local_tables: dict[str, LargeScaleKV] = {}
        if name not in self._local_tables:
            self._local_tables[name] = LargeScaleKV(dim)
        return self._local_tables[name]
