"""Parameter-server runtime — host-resident sharded KV over TCP.

Reference chain this replaces: `listen_and_serv` event loop
(operators/distributed_ops/listen_and_serv_op.cc:352), gRPC/BRPC transport
(operators/distributed/grpc/), `large_scale_kv.h` in-memory sparse table,
and the fleet runtime glue (distributed/fleet/runtime/
parameter_server_runtime.py).  TPU stance (SURVEY §7): embedding tables
that FIT in HBM should use the mesh-sharded design in
paddle_tpu.parallel.embedding; this host tier serves the beyond-HBM
PaddleRec configs, with key-hash sharding across servers over the
fault-tolerant RPC layer in runtime/rpc.py (data-only wire format with
optional HMAC handshake — no pickle anywhere on the receive path;
one request per pull/push batch — the Communicator's merge semantics
come from batched numpy application).

Fault tolerance (docs/PS_WIRE_PROTOCOL.md): clients retry with
deadlines/backoff and stable request ids; the server dedups mutating
ops, snapshots its tables to distributed/fs.py storage, and
`PSServer.restart_from_snapshot` resumes a killed shard so workers
reconnect instead of restarting the job.
"""
from __future__ import annotations

import itertools
import json
import os
import queue
import re
import socketserver
import threading
import time
import weakref

import numpy as np

from ....observability import (debug as _debug, flight as _flight,
                               registry as _obs, watchdog as _watchdog)
from .fault_injection import injector
from .ps_ha import (ReplicationHub, StandbyReplicator,
                    note_fenced_write, note_handoff, note_promotion,
                    set_role_gauges)
from .rpc import (PSDeadlineError, PSRemoteError, RpcClient,
                  RpcServerState, TransportStats, _hard_close,
                  serve_connection)

__all__ = ["ParameterServerRuntime", "LargeScaleKV", "PSServer", "PSClient"]

# snapshot-tier telemetry (per-op rpc latency/retries/dedup counters
# live in rpc.py; these cover the durability path's cost)
_SNAPSHOTS = _obs.counter(
    "paddle_tpu_ps_snapshots_total",
    "snapshot files written, by kind (base|delta)", ["kind"])
_SNAPSHOT_BYTES = _obs.counter(
    "paddle_tpu_ps_snapshot_bytes_total",
    "array payload bytes exported to snapshot files", ["kind"])
_SNAPSHOT_SECONDS = _obs.histogram(
    "paddle_tpu_ps_snapshot_write_seconds",
    "wall time of one snapshot file write", ["kind"])

# watchdog token uniqueness across same-endpoint server respawns
_ps_server_ids = itertools.count()


class LargeScaleKV:
    """In-memory sparse table (reference large_scale_kv.h).

    Hot path: the C++ open-addressing core in paddle_tpu/native/kv_store.cc
    (id->slot hash + contiguous row arena, no Python per row). Falls back
    to the vectorized numpy implementation when no toolchain is available
    or PADDLE_TPU_DISABLE_NATIVE is set."""

    def __init__(self, dim: int, init_std: float = 0.01, seed: int = 0):
        self.dim = dim
        self.init_std = init_std
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        self._index: dict[int, int] = {}
        self._data = np.empty((0, dim), np.float32)
        self._lock = threading.Lock()
        self._native = None
        import os
        if not os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
            from ....native import available, NativeKV
            if available():
                self._native = NativeKV(dim, init_std, seed)

    def _ensure(self, keys: np.ndarray) -> np.ndarray:
        """Slots for keys, creating missing rows in one batched init."""
        idx = self._index
        # dedup while preserving order: duplicate new keys in one batch
        # must allocate ONE slot (else start drifts off the data high-water
        # mark and later inserts clobber existing rows)
        missing = list(dict.fromkeys(
            k for k in keys.tolist() if k not in idx))
        if missing:
            start = len(idx)
            fresh = self._rng.normal(
                0, self.init_std,
                (len(missing), self.dim)).astype(np.float32)
            need = start + len(missing)
            if need > len(self._data):
                grow = np.empty((max(need, 2 * len(self._data) + 64),
                                 self.dim), np.float32)
                grow[:len(self._data)] = self._data
                self._data = grow
            self._data[start:start + len(missing)] = fresh
            for i, k in enumerate(missing):
                idx[k] = start + i
        return np.fromiter((idx[k] for k in keys.tolist()), np.int64,
                           len(keys))

    def pull(self, keys: np.ndarray) -> np.ndarray:
        with self._lock:
            if self._native is not None:
                return self._native.pull(keys)
            slots = self._ensure(np.asarray(keys).ravel())
            return self._data[slots].copy()

    def push(self, keys: np.ndarray, grads: np.ndarray, lr: float = 1.0):
        """SGD apply (reference async PS applies grads on arrival);
        duplicate keys accumulate."""
        with self._lock:
            if self._native is not None:
                self._native.push(keys, grads, lr)
                return
            slots = self._ensure(np.asarray(keys).ravel())
            np.add.at(self._data, slots,
                      (-lr * np.asarray(grads)).astype(np.float32))

    def size(self) -> int:
        with self._lock:
            if self._native is not None:
                return self._native.size()
            return len(self._index)

    def rows_for(self, keys: np.ndarray) -> np.ndarray:
        """Current values of EXISTING rows (post-apply read for the WAL
        journal — O(len(keys)·dim), never O(table))."""
        with self._lock:
            ks = np.asarray(keys, np.int64).ravel()
            if self._native is not None:
                return self._native.pull(ks)
            slots = np.fromiter((self._index[int(k)]
                                 for k in ks.tolist()), np.int64,
                                len(ks))
            return self._data[slots].copy()

    def missing_keys(self, keys) -> np.ndarray | None:
        """Keys with no row yet, first-occurrence order (the exact set
        a pull would lazily init) — or None when unknown (native core
        has no membership probe), meaning callers must assume all."""
        with self._lock:
            if self._native is not None:
                return None
            idx = self._index
            return np.fromiter(
                dict.fromkeys(k for k in np.asarray(keys, np.int64)
                              .ravel().tolist() if k not in idx),
                np.int64)

    def apply_rows(self, keys: np.ndarray, rows: np.ndarray):
        """WAL replay: ensure the rows exist — consuming the init RNG
        stream exactly as the original apply did for then-missing keys
        — then assign the journaled post-values. Idempotent; replayed
        in append order from the same base it reproduces data, key→slot
        index, and RNG stream bit-for-bit. (Native path: the pull
        creates missing rows through the native RNG so its stream
        position advances identically too; note base snapshots do not
        capture the native RNG position — a from-scratch or
        journal-only replay is stream-exact, a native base restore is
        value-exact only.)"""
        with self._lock:
            ks = np.asarray(keys, np.int64).ravel()
            vals = np.asarray(rows, np.float32).reshape(len(ks),
                                                        self.dim)
            if self._native is not None:
                self._native.pull(ks)  # create via RNG, then overwrite
                self._native.import_(ks, vals)
                return
            slots = self._ensure(ks)
            self._data[slots] = vals

    def export_state(self) -> dict:
        """Snapshot-ready state: keys/rows plus (numpy path) the RNG
        stream, so rows initialised AFTER a restore reproduce the
        original run bit-for-bit."""
        with self._lock:
            if self._native is not None:
                keys, rows = self._native.export()
                rng = None
            else:
                keys = np.fromiter(self._index, np.int64,
                                   len(self._index))
                slots = np.fromiter(self._index.values(), np.int64,
                                    len(self._index))
                rows = self._data[slots].copy()
                rng = self._rng.get_state()
        st = {"dim": self.dim, "init_std": self.init_std,
              "seed": self.seed, "keys": keys, "rows": rows}
        if rng is not None:
            st["rng"] = {"alg": rng[0],
                         "key": np.asarray(rng[1], np.uint32),
                         "pos": int(rng[2]), "has_gauss": int(rng[3]),
                         "cached": float(rng[4])}
        return st

    def import_state(self, st: dict):
        with self._lock:
            self.dim = int(st["dim"])
            self.init_std = float(st.get("init_std", self.init_std))
            self.seed = int(st.get("seed", self.seed))
            keys = np.asarray(st["keys"], np.int64)
            rows = np.asarray(st["rows"], np.float32)
            if self._native is not None:
                from ....native import NativeKV
                # keep the instance seed so fresh rows created after a
                # restore stay reproducible
                self._native = NativeKV(self.dim, self.init_std,
                                        self.seed)
                if len(keys):
                    self._native.import_(keys, rows)
                return
            self._data = np.ascontiguousarray(rows)
            self._index = {int(k): i for i, k in enumerate(keys)}
            rng = st.get("rng")
            if rng is not None:
                self._rng.set_state((
                    str(rng["alg"]), np.asarray(rng["key"], np.uint32),
                    int(rng["pos"]), int(rng["has_gauss"]),
                    float(rng["cached"])))

    def save(self, path: str):
        """Persist as npz (data-only; loads with allow_pickle=False)."""
        st = self.export_state()
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, dim=np.int64(st["dim"]),
                     init_std=np.float64(st["init_std"]),
                     keys=st["keys"], rows=st["rows"])
        os.replace(tmp, path)

    def load(self, path: str):
        with np.load(path, allow_pickle=False) as blob:
            self.import_state({"dim": int(blob["dim"]),
                               "init_std": float(blob["init_std"]),
                               "keys": blob["keys"],
                               "rows": blob["rows"]})


# transport: runtime/rpc.py frames (header + dtype/shape-tagged ndarray
# segments — data-only, no pickle on the receive path)


class _SyncRound:
    """Sync-mode round state for one PS shard (reference
    RunSyncLoop + send_barrier/fetch_barrier rounds,
    operators/distributed/communicator.h:253 HalfAsync barrier logic):
    push_sync only BUFFERS gradients; the last trainer through the send
    barrier applies the whole round (mean over trainers) before anyone is
    released; the fetch barrier then holds the next round's apply until
    every trainer pulled the fresh values."""

    def __init__(self, trainers: int):
        self.trainers = trainers
        self.cond = threading.Condition()
        self.pending: list[tuple] = []
        self.send_done: set[int] = set()
        self.fetch_done: set[int] = set()
        self.round = 0
        self.fround = 0

    def push(self, item):
        with self.cond:
            self.pending.append(item)

    def send_barrier(self, worker: int, apply_fn) -> int:
        with self.cond:
            self.send_done.add(int(worker))
            if len(self.send_done) >= self.trainers:
                pending, self.pending = self.pending, []
                apply_fn(pending)
                self.send_done.clear()
                self.round += 1
                self.cond.notify_all()
                return self.round
            r = self.round
            if not self.cond.wait_for(lambda: self.round > r, timeout=300):
                raise TimeoutError("send_barrier: trainers missing")
            return self.round

    def fetch_barrier(self, worker: int) -> int:
        with self.cond:
            self.fetch_done.add(int(worker))
            if len(self.fetch_done) >= self.trainers:
                self.fetch_done.clear()
                self.fround += 1
                self.cond.notify_all()
                return self.fround
            fr = self.fround
            if not self.cond.wait_for(lambda: self.fround > fr,
                                      timeout=300):
                raise TimeoutError("fetch_barrier: trainers missing")
            return self.fround


class _DGCRound:
    """One sparse-gradient exchange round (DGC transport): trainers push
    their top-k (idx, val) pairs; once every trainer has pushed, pulls
    return the MERGED sparse gradient (duplicate indices summed,
    vectorized at seal time). The round recycles when every trainer has
    pulled — lockstep rounds like the reference's sparse allreduce.
    Stragglers raise TimeoutError (matching _SyncRound) instead of
    hanging the handler thread."""

    def __init__(self, trainers: int):
        self.trainers = trainers
        self.cond = threading.Condition()
        self._reset()

    def _reset(self):
        self.parts: list = []
        self.pushed: set[int] = set()
        self.pulled: set[int] = set()
        self.merged = None

    def push(self, worker: int, idx, val):
        with self.cond:
            if not self.cond.wait_for(
                    lambda: worker not in self.pushed, timeout=300):
                raise TimeoutError(
                    "dgc round not drained — a trainer never pulled")
            self.parts.append((np.asarray(idx, np.int64).ravel(),
                               np.asarray(val, np.float32).ravel()))
            self.pushed.add(worker)
            if len(self.pushed) == self.trainers:
                allidx = np.concatenate([p[0] for p in self.parts])
                allval = np.concatenate([p[1] for p in self.parts])
                uniq, inv = np.unique(allidx, return_inverse=True)
                summed = np.bincount(inv, weights=allval,
                                     minlength=len(uniq))
                self.merged = (uniq, summed.astype(np.float32))
                self.cond.notify_all()
            return True

    def pull(self, worker: int):
        with self.cond:
            if not self.cond.wait_for(lambda: self.merged is not None,
                                      timeout=300):
                raise TimeoutError(
                    "dgc round incomplete — trainers missing: "
                    f"{sorted(set(range(self.trainers)) - self.pushed)}")
            idx, val = self.merged
            self.pulled.add(worker)
            if len(self.pulled) == self.trainers:
                self._reset()
                self.cond.notify_all()
            return {"idx": idx, "val": val}


class _InvalSub:
    """One subscriber's invalidation feed: a bounded event queue plus
    an overflow set of tables owed a WHOLE-table invalidation (losing
    an event must degrade to over-invalidation, never staleness)."""

    def __init__(self, maxsize: int):
        self.q: queue.Queue = queue.Queue(maxsize)
        self.lost: set[str] = set()
        self.lock = threading.Lock()


class PSServer(socketserver.ThreadingTCPServer):
    """One PS shard: serves pull/push/save/size for its tables (reference
    listen_and_serv_op RunAsyncLoop — apply-on-arrival, no global
    barrier; RunSyncLoop when the sync ops are used). Port 0 binds an
    ephemeral port; `endpoint` reports it.

    Graceful degradation: with `snapshot_dir` set (arg or
    PADDLE_PS_SNAPSHOT_DIR), the shard snapshots its tables + dedup
    state every `snapshot_every` applied pushes (and every
    `snapshot_interval` seconds) and restores them on construction, so
    a killed shard resumes via `restart_from_snapshot` while clients
    retry-reconnect. Recovery covers the async push path; sync/DGC
    round state is volatile by design (those jobs restart the round)."""

    allow_reuse_address = True
    daemon_threads = True

    # ops that never mutate server state: exempt from dedup caching
    # (subscribe_inval only touches the subscriber registry — replaying
    # a subscription must open a fresh stream, never a cached reply;
    # same for the pub_watch version-announce stream)
    READ_OPS = frozenset({"pull", "size", "ping", "lost_workers",
                          "heartbeat", "metrics", "debug_dump",
                          "subscribe_inval", "pub_latest", "pub_get",
                          "pub_list", "pub_watch",
                          # telemetry verbs (hosted collector): pushes
                          # are single-attempt fire-and-forget, the
                          # rest are reads — none need replay dedup
                          "tel_push", "tel_ping", "tel_fleet",
                          "tel_trace", "tel_traces", "tel_stats",
                          "tel_watch", "tsdb_query", "alerts",
                          "usage_report",
                          # HA plane: replication streams/acks and
                          # status probes must never replay from the
                          # dedup cache (ha_promote/ha_handoff stay
                          # mutating — a retried promote must return
                          # its cached verdict, not re-run)
                          "repl_watch", "repl_ack", "ha_status"})
    # mutating ops whose effects the snapshot tier persists
    _SNAPSHOT_OPS = frozenset({"push", "send_barrier"})
    # verbs that legitimately block on straggler trainers (or, for
    # subscribe_inval / pub_watch, sit open for the subscriber's
    # lifetime): they never count as in-flight work for the stall
    # watchdog (a barrier waiting out a slow trainer is round
    # semantics, not a wedged server)
    _BLOCKING_OPS = frozenset({"send_barrier", "fetch_barrier",
                               "dgc_push", "dgc_pull",
                               "subscribe_inval", "pub_watch",
                               "tel_watch",
                               # replication streams sit open for the
                               # standby's lifetime; handoff blocks on
                               # standby catch-up by design
                               "repl_watch", "ha_handoff"})
    # ops a standby (or a fenced ex-primary) still answers: liveness,
    # observability, the replication/ack plane, and promotion itself
    _HA_CTRL_OPS = frozenset({"ping", "metrics", "debug_dump",
                              "heartbeat", "lost_workers", "ha_status",
                              "ha_promote", "repl_ack", "repl_watch"})

    def __init__(self, endpoint: str, worker_timeout: float = 60.0,
                 snapshot_dir: str | None = None,
                 snapshot_every: int | None = None,
                 snapshot_interval: float | None = None,
                 secret: str | None = None, fs=None,
                 auto_restore: bool = True,
                 wal: bool | None = None,
                 wal_bg_replay: bool | None = None,
                 publish_dir: str | None = None,
                 publish_every_steps: int | None = None,
                 publish_every_seconds: float | None = None,
                 publish_every_rows: int | None = None,
                 primary: str | None = None,
                 ha_epoch: int | None = None,
                 tier_warm_bytes: int | None = None,
                 tier_store_dir: str | None = None,
                 tier_tables=None):
        host, port = endpoint.rsplit(":", 1)
        self.tables: dict[str, LargeScaleKV] = {}
        self._tables_lock = threading.Lock()
        self._sync: _SyncRound | None = None
        self._sync_lock = threading.Lock()
        # worker liveness (reference operators/distributed/
        # heart_beat_monitor.h:54): last-seen stamp per worker id;
        # lost_workers() reports ids silent past the timeout
        self.worker_timeout = worker_timeout
        self._beats: dict[int, float] = {}
        self._dgc: dict[str, _DGCRound] = {}
        self._beats_lock = threading.Lock()
        # hot-row invalidation pub/sub (PR 11): every applied push
        # publishes {table, keys} to each subscriber's bounded queue;
        # the subscribe_inval stream drains it over server-push frames.
        # A queue overflow degrades to a whole-table invalidation
        # marker instead of dropping keys silently.
        self._inval_lock = threading.Lock()
        self._inval_subs: dict[int, "_InvalSub"] = {}
        self._inval_ids = itertools.count()
        self._inval_queue_max = int(os.environ.get(
            "PADDLE_PS_INVAL_QUEUE", "1024") or 0)
        self.inval_published = 0   # events fanned out (tests/bench)

        env = os.environ.get
        self.snapshot_dir = snapshot_dir \
            if snapshot_dir is not None \
            else (env("PADDLE_PS_SNAPSHOT_DIR") or None)
        self.snapshot_every = snapshot_every \
            if snapshot_every is not None \
            else int(env("PADDLE_PS_SNAPSHOT_EVERY", "64") or 0)
        self.snapshot_interval = snapshot_interval \
            if snapshot_interval is not None \
            else float(env("PADDLE_PS_SNAPSHOT_INTERVAL", "0") or 0)
        self.snapshot_compact_every = int(
            env("PADDLE_PS_SNAPSHOT_COMPACT_EVERY", "64") or 0)
        # row-level WAL tier (ROADMAP: "a delta still rewrites the
        # whole dirty table"): with wal on, a push journals only its
        # touched ROWS (paddle_tpu.checkpoint.wal) and durability is
        # write-through by construction; full base snapshots happen
        # only at the compaction threshold. Restore = base + journal
        # replay. Opt-in (PADDLE_PS_WAL / wal=True) — the delta-npz
        # tier stays the default.
        self.wal_enabled = wal if wal is not None \
            else env("PADDLE_PS_WAL", "") not in ("", "0")
        self.wal_compact_bytes = int(
            env("PADDLE_PS_WAL_COMPACT_BYTES", str(64 << 20)) or 0)
        if self.wal_enabled and not self.snapshot_dir:
            raise ValueError(
                "PADDLE_PS_WAL needs a snapshot dir "
                "(PADDLE_PS_SNAPSHOT_DIR) for its base snapshots")
        self._wal = None
        self._wal_pending = False
        # tiered embedding store (docs/PS_TIERED.md): opt-in per
        # server; tables named in tier_tables (every table when empty)
        # hold warm rows in RAM under the byte budget and demand-page
        # cold rows from a local chunk store. Snapshots/WAL/HA are
        # unchanged: TieredTable exports materialize cold rows, so
        # every downstream consumer sees flat keys/rows state.
        self.tier_warm_bytes = int(
            tier_warm_bytes if tier_warm_bytes is not None
            else env("PADDLE_PS_TIER_WARM_BYTES", "0") or 0)
        tt = tier_tables if tier_tables is not None \
            else env("PADDLE_PS_TIER_TABLES", "")
        self.tier_tables = {s.strip() for s in tt.split(",")
                            if s.strip()} \
            if isinstance(tt, str) else set(tt)
        self.tier_store_dir = tier_store_dir \
            if tier_store_dir is not None \
            else (env("PADDLE_PS_TIER_STORE_DIR") or None)
        if self.tier_warm_bytes > 0 and not self.tier_store_dir:
            if not self.snapshot_dir:
                raise ValueError(
                    "PADDLE_PS_TIER_WARM_BYTES needs a cold-store "
                    "dir (PADDLE_PS_TIER_STORE_DIR, or a snapshot "
                    "dir to default under)")
            self.tier_store_dir = os.path.join(self.snapshot_dir,
                                               "tier_store")
        self.tier_demote_interval = float(
            env("PADDLE_PS_TIER_DEMOTE_INTERVAL", "0.05") or 0)
        self._tier_store = None  # lazy CheckpointStore
        self._tier_lock = threading.Lock()
        # high-availability plane (docs/PS_HA.md): a shard started
        # with a primary endpoint is a hot STANDBY — it rejects normal
        # traffic with not_primary and tracks the primary row-for-row
        # over the repl_watch stream until promoted. The shard epoch
        # fences zombie ex-primaries: any request carrying a NEWER
        # epoch proves a successor exists, so this instance fences
        # itself and rejects writes with stale_epoch.
        self.ha_primary = primary if primary is not None \
            else (env("PADDLE_PS_HA_PRIMARY") or None)
        self.ha_role = "standby" if self.ha_primary else "primary"
        self.shard_epoch = int(ha_epoch if ha_epoch is not None
                               else env("PADDLE_PS_HA_EPOCH", "0")
                               or 0)
        self._ha_fenced = False
        self._ha_replicator: StandbyReplicator | None = None
        self._ha_replicated_bytes = 0
        self._ha: ReplicationHub | None = None  # built once port bound
        if fs is None:
            from ....distributed.fs import LocalFS
            fs = LocalFS()
        self._fs = fs
        self._snap_lock = threading.Lock()
        self._snap_io_lock = threading.Lock()  # one snapshot writer
        # apply+dedup-commit vs snapshot-export atomicity: concurrent
        # pushes and the exporter share this RLock (engaged only when
        # snapshots are on — commit_scope returns None otherwise), so a
        # restored snapshot can never hold an applied push without its
        # dedup id or vice versa. RLock: the snapshot hook itself runs
        # inside a push's commit scope.
        self._apply_lock = threading.RLock()
        self._snap_seq = 0       # exports, monotone (under apply lock)
        self._snap_written = 0   # newest BASE seq on disk (under io lock)
        self._mutations = 0
        # dirty-table tracking (ROADMAP open item: write-through
        # snapshots were O(all-table bytes) per push): pushes mark their
        # table dirty; a snapshot exports ONLY dirty tables into a delta
        # file unless a full base is due (first snapshot / compaction)
        self._dirty: set[str] = set()
        self._base_written = False
        self._deltas_since_base = 0
        self._last_export_mutations = -1
        self._snap_pending = False   # a DUE snapshot failed; retry owes it
        self.snapshots_taken = 0
        self.full_snapshots = 0
        self.delta_snapshots = 0
        # fleet-telemetry hosting: this shard answers the tel_* verbs
        # (collector role) when PADDLE_TPU_TELEMETRY_HOST=1, so small
        # fleets need no separate collector process
        self._tel_collector = None
        if env("PADDLE_TPU_TELEMETRY_HOST", "") == "1":
            from ....observability.collector import TelemetryCollector
            self._tel_collector = TelemetryCollector()
        self._rpc = RpcServerState(read_ops=self.READ_OPS,
                                   secret=secret,
                                   after_commit=self._after_commit,
                                   commit_scope=self._commit_scope,
                                   after_retry=self._after_retry,
                                   before_reply=self._ha_before_reply)
        outer = self
        # every live handler socket, so server_close/kill can sever
        # them (replication subscribers + inval streams included —
        # peers must see EOF now, not after a full recv timeout)
        self._conns_lock = threading.Lock()
        self._conns: weakref.WeakSet = weakref.WeakSet()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        super().__init__((host, int(port)), Handler)
        self.endpoint = f"{host}:{self.server_address[1]}"
        self._ha = ReplicationHub(self.endpoint)
        set_role_gauges(self.endpoint, self.ha_role, self.shard_epoch)
        # stall watchdog: completed dispatches are this shard's
        # progress counter; the shard is idle while no non-barrier op
        # is in flight, so a quiet server never looks stalled but a
        # wedged dispatch (hung disk, poisoned lock) fires the token.
        # The name carries a unique instance id: a respawned server on
        # the SAME endpoint must not have its token popped when the
        # old instance's finalize runs at GC.
        self._wd_lock = threading.Lock()
        self._wd_inflight = 0
        self._wd_done = 0
        self._wd_name = (f"ps.server.{self.endpoint.replace(':', '_')}"
                         f".{next(_ps_server_ids)}")
        _srv_ref = weakref.ref(self)
        _watchdog.WATCHDOG.watch(
            self._wd_name,
            probe=lambda: (lambda s: None if s is None
                           else s._wd_done)(_srv_ref()),
            idle=lambda: (lambda s: True if s is None
                          else s._wd_inflight == 0)(_srv_ref()))
        weakref.finalize(self, _watchdog.WATCHDOG.unwatch,
                         self._wd_name)
        if auto_restore and self.snapshot_dir \
                and self._fs.is_file(self.snapshot_path):
            self.load_snapshot()
            self._base_written = True
        # WAL replay gate (PR 12): set = fully caught up. Background
        # replay clears it so the shard SERVES during replay — pulls of
        # rows the base/partial replay already holds come back
        # stale-marked, everything else (mutations, row-creating pulls)
        # waits on the event in _replay_gate. Default stays blocking
        # replay (construction returns caught-up).
        self._replay_done = threading.Event()
        self._replay_done.set()
        self.wal_bg_replay = wal_bg_replay if wal_bg_replay is not None \
            else env("PADDLE_PS_WAL_BG_REPLAY", "") not in ("", "0")
        if self.wal_enabled:
            # replay runs even with NO base on disk: before the first
            # compaction the journal alone holds the whole history
            if auto_restore and self.wal_bg_replay:
                self._replay_done.clear()
                # journal hook armed NOW: it no-ops while _wal is None,
                # and every mutating op is gated until _open_wal ran,
                # so no mutation can slip through un-journaled
                self._rpc.journal = self._journal
                threading.Thread(target=self._bg_replay, daemon=True,
                                 name="ps-wal-replay").start()
            else:
                if auto_restore:
                    self._replay_wal()
                self._open_wal()
                self._rpc.journal = self._journal
        # continuous publication (PR 12): route base exports through
        # the publish tier's content-addressed store on a cadence; the
        # pub_* registry verbs ride this server's own wire
        self.publish_dir = publish_dir if publish_dir is not None \
            else (env("PADDLE_TPU_PUBLISH_DIR") or None)
        self._publisher = None
        self._exporter = None
        if self.publish_dir:
            from ....publish import Publisher, PSExporter
            self._publisher = Publisher(
                self.publish_dir,
                run=f"ps:{self.endpoint}")
            self._exporter = PSExporter(
                self, self._publisher,
                every_steps=publish_every_steps,
                every_seconds=publish_every_seconds,
                every_rows=publish_every_rows).start()
        self._snap_stop = threading.Event()
        if self.snapshot_dir and self.snapshot_interval > 0:
            threading.Thread(target=self._snapshot_loop,
                             daemon=True).start()
        if self.ha_role == "standby":
            self._ha_replicator = StandbyReplicator(
                self, self.ha_primary).start()

    def _bg_replay(self):
        """Background WAL replay (PADDLE_PS_WAL_BG_REPLAY): identical
        work to the blocking path — same journal files, same order,
        same dedup re-arming — just behind the read-through gate
        instead of in front of serve_forever. The finally guarantees a
        replay crash still unwedges gated clients (they see the
        table state the partial replay reached; the WAL files are
        still on disk for the next restart)."""
        try:
            self._replay_wal()
        finally:
            try:
                # arm journaling even after a partial replay: appends
                # land after the torn tail recover=True truncated, the
                # same state a blocking restart would reach
                self._open_wal()
            except Exception:
                pass
            self._replay_done.set()

    # -- snapshot/recovery tier ----------------------------------------
    @property
    def snapshot_path(self) -> str | None:
        if not self.snapshot_dir:
            return None
        tag = self.endpoint.replace(":", "_")
        return os.path.join(self.snapshot_dir, f"ps_{tag}.snap.npz")

    def _commit_scope(self, op: str):
        # only the non-blocking async mutations take the shared lock;
        # barrier/DGC dispatch blocks on straggler trainers and their
        # round state is volatile by design (not snapshot-covered)
        if op == "push" and self.snapshot_dir:
            return self._apply_lock
        if op == "ha_handoff":
            # handoff IS the drain: dispatching under the apply lock
            # means every in-flight push has committed + journaled
            # before the catch-up wait, and new pushes queue on the
            # lock — after the epoch flip they dispatch against a
            # demoted server, get not_primary, and redirect with the
            # SAME request id (zero failed pushes)
            return self._apply_lock
        return None

    def _ha_before_reply(self, op: str, req_id: int):
        """RPC-layer hook between dedup commit and reply: semi-sync
        replication holds the push's ack here — OUTSIDE the commit
        scope, so a waiting push never serializes other pushes."""
        if op in self._SNAPSHOT_OPS and self._ha is not None \
                and self._ha.semisync > 0:
            self._ha.wait_semisync(req_id)

    def _after_commit(self, op: str):
        if op not in self._SNAPSHOT_OPS:
            return
        if self._exporter is not None:
            # cadence counters + wake event only — publication IO
            # never runs on the push path
            self._exporter.note_commit(op)
        with self._snap_lock:
            self._mutations += 1
            if self._wal is not None:
                # WAL mode: durability already happened (the journal
                # hook ran inside the commit scope); the only disk work
                # owed here is threshold compaction into a fresh base
                due = bool(self._wal_pending
                           or (self.wal_compact_bytes
                               and self._wal.bytes_written
                               >= self.wal_compact_bytes))
                full = True
            else:
                due = bool(self.snapshot_dir and self.snapshot_every
                           and self._mutations % self.snapshot_every
                           == 0)
                full = None
        if due:
            # _wal_pending is cleared inside snapshot() at rotation
            # time (under the apply lock) — clearing HERE would erase a
            # flag set concurrently by another push's journal failure
            # after our export captured state
            self.snapshot(full=full)

    def _after_retry(self, op: str):
        """Dedup-hit retry of a mutating op: the original after_commit
        may have died mid-snapshot (failed export/write re-merged the
        dirty marks and raised before the reply). Finish that owed
        persistence WITHOUT counting a new mutation. Keyed on the
        explicit failure flag — a merely-dirty table under a stride/
        interval policy (snapshot_every=N>1) is NOT owed a snapshot,
        so flaky-link retries cannot degrade N-stride configs to
        write-through IO."""
        if op not in self._SNAPSHOT_OPS or not self.snapshot_dir:
            return
        with self._snap_lock:
            pending = self._snap_pending or self._wal_pending
        if pending:
            # WAL mode: a failed journal append leaves rows whose exact
            # apply ORDER is unrecoverable — a full base (which rotates
            # the journal and clears _wal_pending under the apply lock)
            # recaptures everything including RNG streams
            self.snapshot(full=True if self._wal is not None else None)

    def _snapshot_loop(self):
        while not self._snap_stop.wait(self.snapshot_interval):
            self.snapshot()

    # -- row-level WAL tier (paddle_tpu.checkpoint.wal) ------------------
    def _wal_path(self, stamp: int) -> str:
        tag = self.endpoint.replace(":", "_")
        return os.path.join(self.snapshot_dir,
                            f"ps_{tag}.wal_{stamp:010d}")

    def _wal_files(self) -> list[tuple[int, str]]:
        """(stamp, path) of every journal on LOCAL disk, by stamp. The
        WAL is a local-disk tier (os.open append path) — remote-fs
        deployments keep bases remote and journals beside the shard."""
        tag = self.endpoint.replace(":", "_")
        prefix = f"ps_{tag}.wal_"
        out = []
        try:
            names = os.listdir(self.snapshot_dir)
        except FileNotFoundError:
            return []
        for f in names:
            if f.startswith(prefix):
                try:
                    out.append((int(f[len(prefix):]),
                                os.path.join(self.snapshot_dir, f)))
                except ValueError:
                    continue
        return sorted(out)

    def _make_journal(self, path: str, recover: bool = False):
        """Every journal on an HA-capable server is a ReplicatedJournal:
        with no subscribers attached the publish is a few dict ops, and
        the moment a standby subscribes it sees records in exactly
        journal append order."""
        from .ps_ha import ReplicatedJournal
        return ReplicatedJournal(path, self._ha, recover=recover)

    def _open_wal(self):
        os.makedirs(self.snapshot_dir, exist_ok=True)
        files = self._wal_files()
        stamp = max(files[-1][0] if files else 0, self._snap_written)
        # recover=True: truncate any torn tail left by the previous
        # incarnation BEFORE appending — records written after garbage
        # would sit beyond every future replay's stop point
        self._wal = self._make_journal(self._wal_path(stamp),
                                       recover=True)

    def _rotate_wal(self, seq: int):
        """Start journal wal_<seq> (records from now on replay on top
        of base seq). Called under the apply lock at base-export time;
        the superseded journals are deleted only once that base COMMITS
        (_write_snapshot_files), so a failed base write loses nothing."""
        from ....checkpoint.wal import RowJournal
        old, self._wal = self._wal, self._make_journal(
            self._wal_path(seq))
        if old is not None:
            old.close()
        RowJournal.note_compaction()
        # tell standbys we folded the journal into a fresh base so
        # they re-anchor (compact their own journal) too
        self._wal.publish_rotate(seq)

    def _replay_wal(self):
        """Rebuild state journaled after the restored base: apply each
        committed rows-record (ensure+assign — idempotent for rows the
        base already holds) and re-arm the dedup cache from journaled
        request ids, so a client retrying across the crash still gets
        exactly-once. Stops cleanly at a torn tail (the crash point)."""
        from ....checkpoint.wal import replay_file
        from .rpc import decode_body
        replayed = 0
        for _stamp, path in self._wal_files():
            for rec in replay_file(path):
                if rec["kind"] == "rows":
                    t = self.table(rec["table"], int(rec["dim"]),
                                   float(rec.get("init_std", 0.01)))
                    t.apply_rows(rec["idx"], rec["values"])
                rid = int(rec.get("req_id", 0))
                if rid:
                    reply = decode_body(rec["extra"]) \
                        if rec["extra"] else True
                    self._rpc.dedup.commit(rid, reply)
                    with self._snap_lock:
                        self._mutations += 1
                replayed += 1
        return replayed

    def _wal_guard(self, append):
        """Run one journal append under the owed-durability contract:
        on failure the mutation is applied (and possibly dedup'd) but
        NOT on disk — flag it so the retry/after_commit hooks recover
        with a full base snapshot (which re-captures the un-journaled
        rows/RNG and rotates the journal), and re-raise so the client
        sees the failure."""
        try:
            return append()
        except BaseException:
            with self._snap_lock:
                self._wal_pending = True
            raise

    def _tier_pull(self, t, keys):
        """Pull with cold-fault accounting: a tiered table reports how
        many rows it demand-paged, and a faulting reply is wrapped
        ``{"v": rows, "cold_faults": n}`` (the replay-gate dict-reply
        precedent) so PSClient can count cold faults per pull."""
        pull_ex = getattr(t, "pull_ex", None)
        if pull_ex is None:
            return t.pull(keys)
        out, faults = pull_ex(keys)
        if faults:
            return {"v": out, "cold_faults": int(faults)}
        return out

    def _wal_pull(self, req: dict):
        """WAL-mode pull. Hot path (every key already has a row): only
        the per-table lock, same as non-WAL mode. A pull that must
        lazily init rows consumes the table RNG, so the created rows
        are journaled — under the apply lock, because the
        create+journal pair must serialize against pushes or replay
        order could diverge from allocation order."""
        t = self.table(req["table"], req["dim"],
                       req.get("init_std", 0.01))
        probe = t.missing_keys(req["keys"])
        if probe is not None and len(probe) == 0:
            # cold faults (tiered tables) happen HERE, off the apply
            # lock — paging in an existing row creates nothing and
            # consumes no RNG, so it needs no journaling
            return self._tier_pull(t, req["keys"])
        with self._apply_lock:
            missing = t.missing_keys(req["keys"])  # re-check under lock
            n0 = t.size()
            out = self._tier_pull(t, req["keys"])
            if missing is not None:
                created = missing
            elif t.size() != n0:  # native: no membership probe —
                created = np.asarray(req["keys"],  # journal full set
                                     np.int64).ravel()
            else:
                created = np.empty(0, np.int64)
            if len(created):
                # journal ONLY the created rows (O(created), not
                # O(pulled)); replay's ensure+assign re-draws the init
                # stream at the same point
                self._mark_dirty(req["table"])
                self._wal_guard(lambda: self._wal.append_rows(
                    req["table"], created, t.rows_for(created),
                    dim=t.dim, init_std=t.init_std, seed=t.seed))
        return out

    def _journal(self, op: str, req: dict, req_id: int, reply):
        """RpcServerState.journal hook — runs INSIDE the commit scope,
        right after the dedup commit. A push journals its touched rows'
        post-values; every other mutating op journals a dedup mark (its
        state effects are either volatile round state or journaled by
        the barrier apply itself)."""
        if self._wal is None:
            return
        from .rpc import encode_body
        if op == "push":
            t = self.tables[req["table"]]
            keys = np.asarray(req["keys"], np.int64).ravel()
            self._wal_guard(lambda: self._wal.append_rows(
                req["table"], keys, t.rows_for(keys), dim=t.dim,
                init_std=t.init_std, seed=t.seed, req_id=req_id,
                extra=encode_body(reply)))
            _flight.record("ps", "wal_commit", endpoint=self.endpoint,
                           op=op, table=req.get("table"),
                           rows=int(keys.size), req_id=req_id)
        else:
            self._wal_guard(lambda: self._wal.append_mark(
                req_id, extra=encode_body(reply)))
            _flight.record("ps", "wal_commit", endpoint=self.endpoint,
                           op=op, rows=0, req_id=req_id)

    def _delta_path(self, seq: int) -> str:
        tag = self.endpoint.replace(":", "_")
        return os.path.join(self.snapshot_dir,
                            f"ps_{tag}.delta_{seq:010d}.npz")

    def _delta_files(self) -> list[tuple[int, str]]:
        """(seq, filename) of every delta on storage, sorted by seq."""
        tag = self.endpoint.replace(":", "_")
        prefix, suffix = f"ps_{tag}.delta_", ".npz"
        _dirs, files = self._fs.ls_dir(self.snapshot_dir)
        out = []
        for f in files:
            if f.startswith(prefix) and f.endswith(suffix):
                try:
                    out.append((int(f[len(prefix):-len(suffix)]), f))
                except ValueError:
                    continue
        return sorted(out)

    def snapshot(self, full: bool | None = None):
        """Consistent table+dedup snapshot. Runs before the mutating
        reply is sent (`after_commit` hook), so a crash between apply
        and reply still resolves to exactly-once: the retried request
        hits the restored dedup set.

        Incremental tier (ROADMAP open item): the first snapshot (and
        every `snapshot_compact_every`-th thereafter) writes the full
        base npz; in between, a snapshot writes a DELTA npz holding
        only the tables dirtied since the previous export, plus the
        dedup/mutation state, so write-through durability
        (PADDLE_PS_SNAPSHOT_EVERY=1) costs O(touched-table bytes) per
        push instead of O(all-table bytes). Restore = base + deltas in
        sequence order; base writes garbage-collect superseded deltas.

        Locking: the EXPORT runs under `_apply_lock` (tables, dirty
        set, and dedup ids must come from the same instant, or a
        crash-restore could double-apply or drop a concurrent worker's
        push); the npz write runs under `_snap_io_lock` only, so
        concurrent pushes proceed during disk IO. Lock order is always
        apply -> io (the push-commit path enters here already holding
        the apply RLock). A slow older BASE writer is kept from
        clobbering a newer base by the sequence check; delta files are
        per-seq, so late writes cannot clobber anything and the
        seq-ordered replay at load time makes write order irrelevant.

        Known benign race: before the FIRST base write lands on disk,
        concurrent exporters each see _base_written=False and export a
        redundant full base (the io-side seq check discards all but
        the newest). Pure transient startup IO — deciding the base
        optimistically instead would let a racing DELTA land on disk
        with no base beneath it, turning a crash in that window into
        real data loss, so the wasted export is the correct trade."""
        path = self.snapshot_path
        if path is None:
            return
        with self._apply_lock:
            with self._snap_lock:
                dirty = set(self._dirty)
                self._dirty.clear()
            if full is not True and self._base_written and not dirty \
                    and self._mutations == self._last_export_mutations:
                # nothing changed since the last export: an idle server
                # on a snapshot_interval timer must not churn empty
                # deltas (or periodic full bases) forever
                return
            self._snap_seq += 1
            seq = self._snap_seq
            try:
                if self._wal is not None:
                    # WAL mode has no deltas: every snapshot is a full
                    # base that compacts the journal. Rotate FIRST
                    # (still under the apply lock): rows applied after
                    # this instant land in wal_<seq>, which is exactly
                    # what replays on top of base seq. Any owed
                    # persistence (_wal_pending) is satisfied by this
                    # export — clearing it under the apply lock means a
                    # journal failure racing us either happened before
                    # (rows captured by this export) or will set the
                    # flag after we clear it (kept for the next base)
                    do_full = True
                    self._rotate_wal(seq)
                    with self._snap_lock:
                        self._wal_pending = False
                else:
                    do_full = full if full is not None else (
                        not self._base_written
                        or (self.snapshot_compact_every
                            and self._deltas_since_base
                            >= self.snapshot_compact_every))
                arrays = self._export_arrays(
                    seq, names=None if do_full else dirty,
                    kind="base" if do_full else "delta")
                self._last_export_mutations = self._mutations
            except BaseException:
                with self._snap_lock:
                    self._dirty |= dirty
                    self._snap_pending = True
                raise
        try:
            self._write_snapshot_files(path, arrays, seq, do_full)
        except BaseException:
            # the dirty marks were consumed by this export; a failed
            # export/write must put them back (and flag the owed
            # snapshot for the retry hook) or every later delta would
            # silently omit these tables until the next full base
            with self._snap_lock:
                self._dirty |= dirty
                self._snap_pending = True
            raise
        with self._snap_lock:
            self._snap_pending = False

    def _write_snapshot_files(self, path, arrays, seq, do_full):
        kind = "base" if do_full else "delta"
        t0 = time.perf_counter()
        with self._snap_io_lock:
            if do_full:
                if seq <= self._snap_written:
                    # a newer base already reached disk; our dirty set
                    # is covered by it (exported later = superset state)
                    return
                self._write_snapshot(path, arrays)
                self._snap_written = seq
                self._base_written = True
                self._deltas_since_base = 0
                self.full_snapshots += 1
                for dseq, fname in self._delta_files():
                    if dseq <= seq:
                        self._fs.delete(
                            os.path.join(self.snapshot_dir, fname))
                if self._wal is not None:
                    # journals superseded by this base (their rows are
                    # all ≤ the base's export instant)
                    for wseq, wpath in self._wal_files():
                        if wseq < seq:
                            try:
                                os.unlink(wpath)
                            except OSError:
                                pass
            else:
                self._write_snapshot(self._delta_path(seq), arrays)
                self._deltas_since_base += 1
                self.delta_snapshots += 1
            self.snapshots_taken += 1
        if do_full and self._tier_store is not None:
            # fold the cold store's garbage in with base compaction:
            # chunks no live segment references (age-guarded, so a
            # segment mid-write is never collected) are dropped here
            from .tiered_store import gc_cold_store
            with self._tables_lock:
                ts = list(self.tables.values())
            gc_cold_store(self._tier_store, ts)
        dt = time.perf_counter() - t0
        nbytes = sum(a.nbytes for a in arrays.values())
        _SNAPSHOT_SECONDS.labels(kind=kind).observe(dt)
        _SNAPSHOTS.labels(kind=kind).inc()
        _SNAPSHOT_BYTES.labels(kind=kind).inc(nbytes)
        _flight.record("ps", "snapshot", endpoint=self.endpoint,
                       kind=kind, seq=seq, bytes=int(nbytes),
                       seconds=round(dt, 6))

    def _export_arrays(self, seq: int = 0, names: set | None = None,
                       kind: str = "base") -> dict:
        arrays: dict[str, np.ndarray] = {}
        meta = {"version": 2, "kind": kind, "seq": seq,
                "endpoint": self.endpoint,
                "mutations": self._mutations, "tables": {}}
        with self._tables_lock:
            items = [(n, t) for n, t in self.tables.items()
                     if names is None or n in names]
        for name, t in items:
            st = t.export_state()
            tmeta = {"dim": st["dim"], "init_std": st["init_std"],
                     "seed": st["seed"]}
            arrays[f"k:{name}"] = st["keys"]
            arrays[f"r:{name}"] = st["rows"]
            rng = st.get("rng")
            if rng is not None:
                tmeta["rng"] = {"alg": rng["alg"], "pos": rng["pos"],
                                "has_gauss": rng["has_gauss"],
                                "cached": rng["cached"]}
                arrays[f"s:{name}"] = rng["key"]
            meta["tables"][name] = tmeta
        ids, blobs = self._rpc.dedup.export()
        arrays["dedup_ids"] = ids
        arrays["dedup_lens"] = np.array([len(b) for b in blobs],
                                        np.int64)
        arrays["dedup_blob"] = np.frombuffer(
            b"".join(blobs), np.uint8) if blobs else \
            np.empty(0, np.uint8)
        arrays["meta"] = np.frombuffer(
            json.dumps(meta).encode("utf-8"), np.uint8)
        return arrays

    def _write_snapshot(self, path: str, arrays: dict):
        from ....distributed.fs import LocalFS
        self._fs.mkdirs(self.snapshot_dir)
        if isinstance(self._fs, LocalFS):
            # fast path: write beside the target, atomic rename
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            self._fs.mv(tmp, path, overwrite=True)
            return
        # remote fs (HDFSClient &co): stage locally, upload, rename
        import tempfile
        fd, local = tempfile.mkstemp(suffix=".snap.npz")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            remote_tmp = f"{path}.tmp"
            self._fs.delete(remote_tmp)
            self._fs.upload(local, remote_tmp)
            self._fs.mv(remote_tmp, path, overwrite=True)
        finally:
            if os.path.exists(local):
                os.unlink(local)

    def load_snapshot(self, path: str | None = None):
        """Restore base + every delta with a newer sequence number, in
        sequence order (each delta replaces the tables it names and the
        full dedup/mutation state it captured — last write wins)."""
        base_meta = self._load_one(path or self.snapshot_path,
                                   replace=True)
        last_seq = int(base_meta.get("seq", 0))
        if self.snapshot_dir:
            for dseq, fname in self._delta_files():
                if dseq <= last_seq:
                    continue
                self._load_one(os.path.join(self.snapshot_dir, fname),
                               replace=False)
                last_seq = dseq
        with self._apply_lock:
            self._snap_seq = max(self._snap_seq, last_seq)
        self._snap_written = max(self._snap_written,
                                 int(base_meta.get("seq", 0)))

    def _load_one(self, path: str, replace: bool) -> dict:
        from ....distributed.fs import LocalFS
        local = path
        staged = None
        if not isinstance(self._fs, LocalFS):
            import tempfile
            fd, staged = tempfile.mkstemp(suffix=".snap.npz")
            os.close(fd)
            os.unlink(staged)  # fs.download copies onto a fresh path
            self._fs.download(path, staged)
            local = staged
        try:
            return self._load_snapshot_file(local, replace)
        finally:
            if staged and os.path.exists(staged):
                os.unlink(staged)

    def _load_snapshot_file(self, path: str, replace: bool = True) -> dict:
        with np.load(path, allow_pickle=False) as blob:
            return self._import_snapshot_blob(blob, replace)

    def _import_snapshot_blob(self, blob, replace: bool = True) -> dict:
        """Import one exported state blob (an open npz file OR the
        same arrays as a plain dict — the HA bootstrap arrives as a
        dict over the wire) into tables + dedup + mutation count."""
        meta = json.loads(bytes(blob["meta"]).decode("utf-8"))

        def writable(a):
            # wire-decoded arrays (HA bootstrap) view read-only
            # frombuffer memory; tables update rows in place
            a = np.asarray(a)
            return a if a.flags.writeable else a.copy()

        tables: dict[str, LargeScaleKV] = {}
        for name, tmeta in meta["tables"].items():
            t = self._make_table(name, int(tmeta["dim"]),
                                 init_std=float(tmeta["init_std"]),
                                 seed=int(tmeta["seed"]))
            st = {"dim": tmeta["dim"],
                  "init_std": tmeta["init_std"],
                  "seed": tmeta["seed"],
                  "keys": writable(blob[f"k:{name}"]),
                  "rows": writable(blob[f"r:{name}"])}
            if "rng" in tmeta:
                st["rng"] = dict(tmeta["rng"],
                                 key=writable(blob[f"s:{name}"]))
            t.import_state(st)
            tables[name] = t
        ids = blob["dedup_ids"]
        lens = blob["dedup_lens"].tolist()
        raw = blob["dedup_blob"].tobytes()
        blobs, off = [], 0
        for n in lens:
            blobs.append(raw[off:off + n])
            off += n
        with self._tables_lock:
            if replace:
                old, self.tables = self.tables, tables
            else:
                old = {}
                self.tables.update(tables)
        for t in old.values():
            # replaced tiered tables must stop their demoter threads
            close = getattr(t, "close", None)
            if close is not None:
                close()
        self._rpc.dedup.import_(ids, blobs)
        with self._snap_lock:
            self._mutations = int(meta.get("mutations", 0))
        return meta

    @classmethod
    def restart_from_snapshot(cls, endpoint: str, snapshot_dir: str,
                              **kwargs) -> "PSServer":
        """Bring a killed shard back on its endpoint, restoring tables,
        dedup ids, and RNG streams from the latest snapshot (workers'
        retry loops reconnect on their own)."""
        return cls(endpoint, snapshot_dir=snapshot_dir,
                   auto_restore=True, **kwargs)

    def server_close(self):
        self._snap_stop.set()
        with self._tables_lock:
            ts = list(self.tables.values())
        for t in ts:
            close = getattr(t, "close", None)
            if close is not None:
                close()  # stop tiered tables' demoter threads
        rep = self._ha_replicator
        if rep is not None:
            rep.close()
        if self._exporter is not None:
            self._exporter.stop()
        if self._wal is not None:
            self._wal.close()
        super().server_close()
        # sever every live handler socket (the PR 11 lesson, extended
        # to the HA plane): replication subscribers and inval streams
        # must see EOF NOW so a standby detects primary death within
        # its heartbeat interval, not after a full recv timeout
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            _hard_close(s)

    def kill(self):
        """Stop serving AND sever every open connection — the
        in-process stand-in for shard death (chaos drills): attached
        standbys see the stream break immediately."""
        self.shutdown()
        self.server_close()

    def _tier_store_handle(self):
        """Lazy shared CheckpointStore for every tiered table's cold
        segments (content-addressed chunks dedup across tables)."""
        with self._tier_lock:
            if self._tier_store is None:
                from ....checkpoint.store import CheckpointStore
                os.makedirs(self.tier_store_dir, exist_ok=True)
                self._tier_store = CheckpointStore(self.tier_store_dir,
                                                   keep=0)
            return self._tier_store

    def _make_table(self, name: str, dim: int, init_std: float = 0.01,
                    seed: int = 0) -> LargeScaleKV:
        if self.tier_warm_bytes > 0 and (
                not self.tier_tables or name in self.tier_tables):
            from .tiered_store import TieredTable
            return TieredTable(
                dim, init_std=init_std, seed=seed,
                store=self._tier_store_handle(), name=name,
                warm_bytes=self.tier_warm_bytes,
                demote_interval=self.tier_demote_interval)
        return LargeScaleKV(dim, init_std=init_std, seed=seed)

    def table(self, name: str, dim: int,
              init_std: float = 0.01) -> LargeScaleKV:
        with self._tables_lock:
            if name not in self.tables:
                self.tables[name] = self._make_table(name, dim,
                                                     init_std)
            return self.tables[name]

    def _mark_dirty(self, name: str):
        with self._snap_lock:
            self._dirty.add(name)

    # -- hot-row invalidation pub/sub (PR 11) ---------------------------
    def _publish_inval(self, table: str, keys):
        """Fan an applied push's {table, keys} out to every subscriber.
        Non-blocking: a full queue records the table in the
        subscriber's overflow set (-> whole-table invalidation) so a
        slow subscriber can never stall the push path."""
        with self._inval_lock:
            subs = list(self._inval_subs.values())
        if not subs:
            return
        keys = np.asarray(keys, np.int64).ravel().copy()
        ev = {"table": table, "keys": keys}
        for s in subs:
            try:
                s.q.put_nowait(ev)
            except queue.Full:
                with s.lock:
                    s.lost.add(table)
        self.inval_published += 1

    def _subscribe_inval(self):
        """Dispatch generator for the subscribe_inval op: registers a
        subscriber and streams its events as server-push frames until
        the client cancels (F_CANCEL -> GeneratorExit) or disconnects.
        Keepalive frames every few seconds keep the stream's cancel
        check live while the shard is idle."""
        sub = _InvalSub(self._inval_queue_max)
        with self._inval_lock:
            sid = next(self._inval_ids)
            self._inval_subs[sid] = sub
        try:
            yield {"subscribed": True}
            while True:
                with sub.lock:
                    lost, sub.lost = sub.lost, set()
                for t in sorted(lost):
                    yield {"table": t, "full": True}
                try:
                    ev = sub.q.get(timeout=5.0)
                except queue.Empty:
                    yield {"keepalive": True}
                    continue
                yield ev
        finally:
            with self._inval_lock:
                self._inval_subs.pop(sid, None)

    # -- high availability (docs/PS_HA.md) ------------------------------
    def _ha_gate(self, op: str, req_epoch: int):
        """Role/epoch admission check, before the op switch. Standbys
        answer only the control plane (everything else redirects via
        not_primary). A primary that sees a request carrying a NEWER
        epoch has proof a successor was promoted: it fences itself and
        rejects writes with stale_epoch — a zombie ex-primary can
        never fork the shard."""
        if self.ha_role == "standby":
            if op in self._HA_CTRL_OPS:
                return
            raise ValueError(
                f"not_primary primary={self.ha_primary or ''} "
                f"epoch={self.shard_epoch}")
        if req_epoch > self.shard_epoch and not self._ha_fenced:
            self._ha_fenced = True
            _flight.record("ps", "ha_fenced", endpoint=self.endpoint,
                           epoch=self.shard_epoch,
                           req_epoch=req_epoch)
        if self._ha_fenced and op not in self._HA_CTRL_OPS:
            if op not in self.READ_OPS:
                note_fenced_write(self.endpoint, op, req_epoch,
                                  self.shard_epoch)
            raise ValueError(f"stale_epoch epoch={self.shard_epoch}")

    def _repl_watch(self, req: dict):
        """Dispatch generator for repl_watch: one standby's replication
        feed. Subscribing and exporting the bootstrap state under the
        apply lock guarantees no record committed after the bootstrap
        can be missed (duplicates across the boundary are benign —
        the standby skips already-applied sequence numbers)."""
        # a bg-replaying primary applies journal records WITHOUT
        # publishing them — bootstrapping mid-replay would hand the
        # standby partial state with no stream to fill the rest
        self._replay_done.wait()
        if self._wal is None:
            raise ValueError(
                "repl_watch needs the WAL tier (PADDLE_PS_WAL=1 with "
                "a snapshot dir) on the primary")
        name = str(req.get("name", "?"))
        hub = self._ha
        sub = None
        try:
            with self._apply_lock:
                sub = hub.subscribe(name)
                arrays = self._export_arrays(self._snap_seq,
                                             names=None, kind="base")
                start_seq = hub.seq
                epoch = self.shard_epoch
            yield {"bootstrap": arrays, "seq": start_seq,
                   "epoch": epoch, "sub": sub.sid,
                   "primary": self.endpoint}
            _flight.record("ps", "ha_standby_attach",
                           endpoint=self.endpoint, peer=name,
                           seq=start_seq)
            inj = injector()
            while True:
                if sub.broken:
                    raise ValueError(
                        "replication queue overflow — resync")
                try:
                    rec = sub.q.get(timeout=5.0)
                except queue.Empty:
                    yield {"kind": "keepalive",
                           "epoch": self.shard_epoch}
                    continue
                if inj.active:
                    act = inj.repl_fault(int(rec.get("seq", 0)))
                    if act is not None:
                        action, delay = act
                        if action == "drop":
                            continue  # standby sees the gap -> resync
                        if action == "delay":
                            time.sleep(delay)
                        elif action == "corrupt" \
                                and rec.get("kind") == "rows":
                            bad = np.array(rec["values"], np.float32,
                                           copy=True)
                            if bad.size:
                                bad.flat[0] += 1.0
                            rec = dict(rec, values=bad)  # crc now lies
                yield rec
        finally:
            if sub is not None:
                hub.unsubscribe(sub)

    def _ha_import_bootstrap(self, arrays: dict, seq: int, epoch: int):
        """Standby: replace local state with the primary's bootstrap
        export (tables + RNG streams + dedup cache), adopt its epoch,
        and re-anchor our own journal with a fresh full base."""
        with self._apply_lock:
            self._import_snapshot_blob(arrays, replace=True)
            if epoch > self.shard_epoch:
                self.shard_epoch = int(epoch)
                set_role_gauges(self.endpoint, self.ha_role,
                                self.shard_epoch)
            self._ha_replicated_bytes = 0
        _flight.record("ps", "ha_bootstrap", endpoint=self.endpoint,
                       primary=self.ha_primary or "", seq=int(seq),
                       epoch=int(epoch))
        if self._wal is not None:
            self.snapshot(full=True)

    def _ha_apply_record(self, rec: dict):
        """Standby: apply one replicated journal record through the
        same ensure+assign path WAL replay uses, journal it to our OWN
        journal (so a promoted standby restarts from its own disk),
        and commit the request id + reply into the dedup cache —
        exactly-once is preserved across failover."""
        from .rpc import decode_body
        extra = b""
        if "extra" in rec and len(rec["extra"]):
            extra = np.asarray(rec["extra"], np.uint8).tobytes()
        kind = rec.get("kind")
        with self._apply_lock:
            n = 0
            if kind == "rows":
                t = self.table(rec["table"], int(rec["dim"]),
                               float(rec.get("init_std", 0.01)))
                idx = np.asarray(rec["idx"], np.int64).ravel()
                t.apply_rows(idx, rec["values"])
                self._mark_dirty(rec["table"])
                if self._wal is not None:
                    n = self._wal_guard(
                        lambda: self._wal.append_rows(
                            rec["table"], idx,
                            np.asarray(rec["values"], np.float32),
                            dim=int(rec["dim"]),
                            init_std=float(rec.get("init_std", 0.01)),
                            seed=int(rec.get("seed", 0)),
                            req_id=int(rec.get("req_id", 0)),
                            extra=extra))
                else:
                    idx_b = idx.nbytes
                    n = int(np.asarray(rec["values"]).nbytes + idx_b)
            elif kind == "mark":
                if self._wal is not None:
                    n = self._wal_guard(
                        lambda: self._wal.append_mark(
                            int(rec.get("req_id", 0)), extra=extra))
            rid = int(rec.get("req_id", 0))
            if rid:
                self._rpc.dedup.commit(
                    rid, decode_body(extra) if extra else True)
                with self._snap_lock:
                    self._mutations += 1
            self._ha_replicated_bytes += n

    def _ha_note_rotate(self):
        """Standby: the primary compacted its journal into a fresh
        base — compact ours too, so standby disk usage tracks the
        primary's bound."""
        if self._wal is not None:
            self.snapshot(full=True)

    def promote(self, epoch: int) -> dict:
        """Standby -> primary (launcher failover or handoff target):
        adopt the bumped epoch, stop replicating, start serving. On an
        already-primary server this only ratchets the epoch."""
        epoch = int(epoch)
        rep = self._ha_replicator
        applied = int(rep.applied_seq) if rep is not None \
            else int(self._ha.seq)
        if self.ha_role != "primary":
            # order matters: flip the role FIRST so the replicator
            # loop exits instead of resyncing, then sever its stream
            self.ha_role = "primary"
            self.ha_primary = None
            self._ha_replicator = None
            if rep is not None:
                rep.close()
            note_promotion(self.endpoint, max(self.shard_epoch, epoch))
        self.shard_epoch = max(self.shard_epoch, epoch)
        self._ha_fenced = False
        set_role_gauges(self.endpoint, "primary", self.shard_epoch)
        return {"role": "primary", "epoch": int(self.shard_epoch),
                "endpoint": self.endpoint, "applied_seq": applied}

    def _ha_demote(self, new_primary: str, epoch: int):
        """Handoff tail: this ex-primary becomes a standby of the
        freshly promoted target, so the shard keeps a hot spare."""
        self.shard_epoch = int(epoch)
        self.ha_primary = new_primary
        self.ha_role = "standby"
        self._ha_fenced = False
        set_role_gauges(self.endpoint, "standby", self.shard_epoch)
        self._ha_replicator = StandbyReplicator(
            self, new_primary).start()

    def _ha_handoff(self, req: dict) -> dict:
        """Planned handoff (maintenance / shard rebalancing): runs
        UNDER the apply lock (commit_scope), so every in-flight push
        has committed and journaled before the catch-up wait, and new
        pushes queue on the lock — after the flip they redirect to the
        new primary with their SAME request ids. Zero failed pushes."""
        target = str(req.get("target", ""))
        if self.ha_role != "primary":
            raise ValueError(
                f"not_primary primary={self.ha_primary or ''} "
                f"epoch={self.shard_epoch}")
        if self._wal is None:
            raise ValueError("ha_handoff needs the WAL tier")
        sub = self._ha.find(target)
        if sub is None:
            raise ValueError(
                f"ha_handoff: {target!r} is not an attached standby")
        last = self._ha.seq
        if not self._ha.wait_caught_up(
                sub, last, timeout=float(req.get("timeout", 30.0))):
            raise RuntimeError(
                f"ha_handoff: {target} did not catch up to seq "
                f"{last}")
        epoch_new = int(self.shard_epoch) + 1
        cl = RpcClient(target, timeout=10.0, deadline=15.0,
                       max_retries=1)
        try:
            st = cl.call({"op": "ha_promote", "epoch": epoch_new},
                         timeout=10.0)
        finally:
            cl.close()
        self._ha_demote(target, epoch_new)
        note_handoff(self.endpoint, target, epoch_new)
        return {"promoted": target, "epoch": epoch_new,
                "applied_seq": int(st.get("applied_seq", 0))
                if isinstance(st, dict) else 0}

    def ha_status(self) -> dict:
        rep = self._ha_replicator
        return {"role": self.ha_role,
                "epoch": int(self.shard_epoch),
                "endpoint": self.endpoint,
                "primary": self.ha_primary or "",
                "fenced": bool(self._ha_fenced),
                "applied_seq": int(rep.applied_seq)
                if rep is not None else int(self._ha.seq),
                "repl_seq": int(self._ha.seq),
                "resyncs": int(rep.resyncs) if rep is not None else 0,
                "synced": bool(rep.synced.is_set())
                if rep is not None else True,
                "standbys": self._ha.status(),
                "semisync_degraded": int(self._ha.degraded)}

    def _dispatch(self, req: dict):
        """In-flight accounting wrapper around the op switch: arms the
        stall watchdog token (non-barrier ops only), applies the
        hang-injection stall point, and records push/pull flight
        events for the postmortem ring."""
        op = req.get("op")
        track = op not in self._BLOCKING_OPS
        if track:
            with self._wd_lock:
                self._wd_inflight += 1
        inj = injector()
        if inj.active:
            inj.maybe_stall("dispatch", "server")
        try:
            rep = self._dispatch_inner(req)
        finally:
            if track:
                with self._wd_lock:
                    self._wd_inflight -= 1
                    self._wd_done += 1
        if op in ("push", "pull"):
            _flight.record("ps", op, endpoint=self.endpoint,
                           table=req.get("table"),
                           keys=int(np.asarray(req["keys"]).size)
                           if "keys" in req else 0)
        return rep

    def _replay_gate(self, req: dict):
        """Read-through gate while background WAL replay rebuilds
        state. Pulls whose rows ALL exist already (base + replay so
        far) are served immediately, wrapped ``{"v": rows, "stale":
        True}`` so the client knows they predate catch-up. Everything
        that would perturb replay — mutations, and pulls that would
        lazily CREATE rows (row creation consumes the table RNG, so
        out-of-order creation would diverge from journal order) —
        waits on the replay-done event. Pure status reads (ping,
        metrics, ...) pass through. Returns a reply to short-circuit
        with, or None to fall through to the normal op switch."""
        op = req["op"]
        if op == "pull":
            t = self.tables.get(req.get("table"))
            if t is not None:
                probe = t.missing_keys(req["keys"])
                if probe is not None and len(probe) == 0:
                    return {"v": t.pull(req["keys"]), "stale": True}
            self._replay_done.wait()
            return None
        if op in ("ping", "size", "metrics", "debug_dump",
                  "heartbeat", "lost_workers", "subscribe_inval",
                  "tsdb_query", "alerts", "usage_report") \
                or op.startswith("pub_") or op.startswith("tel_"):
            return None
        self._replay_done.wait()
        return None

    def _dispatch_inner(self, req: dict):
        op = req["op"]
        # shard epoch rides the request skeleton (HA fencing); epoch 0
        # = legacy client, always admitted on an unfenced primary
        req_epoch = int(req.pop("_epoch", 0) or 0)
        if req_epoch or self._ha_fenced or self.ha_role != "primary":
            self._ha_gate(op, req_epoch)
        if op == "repl_watch":
            return self._repl_watch(req)
        if op == "repl_ack":
            return self._ha.ack(int(req.get("sub", -1)),
                                int(req.get("seq", 0)),
                                int(req.get("bytes", 0)),
                                float(req.get("t", 0.0)))
        if op == "ha_status":
            return self.ha_status()
        if op == "ha_promote":
            return self.promote(int(req.get("epoch", 0)))
        if op == "ha_handoff":
            return self._ha_handoff(req)
        if not self._replay_done.is_set():
            gated = self._replay_gate(req)
            if gated is not None:
                return gated
        if op.startswith("pub_"):
            # version-registry verbs (PR 12) ride the PS wire when
            # publishing is configured — one endpoint serves pulls AND
            # version announces, so serving subscribers need no extra
            # connection
            if self._publisher is None:
                raise ValueError(
                    "publishing not configured on this shard "
                    "(set PADDLE_TPU_PUBLISH_DIR or publish_dir=)")
            from ....publish.registry import registry_dispatch
            return registry_dispatch(self._publisher.registry, req)
        if op.startswith("tel_") \
                or op in ("tsdb_query", "alerts", "usage_report"):
            # fleet-telemetry verbs (hosted collector): one PS
            # endpoint can double as the collector, the debug_dump /
            # pub_* hosting pattern
            if self._tel_collector is None:
                raise ValueError(
                    "telemetry collector not hosted on this shard "
                    "(set PADDLE_TPU_TELEMETRY_HOST=1)")
            from ....observability.collector import telemetry_dispatch
            return telemetry_dispatch(self._tel_collector, req)
        if op == "pull":
            if self._wal is not None:
                return self._wal_pull(req)
            t = self.table(req["table"], req["dim"],
                           req.get("init_std", 0.01))
            n0 = t.size()
            out = self._tier_pull(t, req["keys"])
            if self.snapshot_dir and t.size() != n0:
                # lazy row init consumed the table's rng stream — the
                # next delta must carry this table even without a push
                self._mark_dirty(req["table"])
            return out
        if op == "push":
            self.table(req["table"], req["dim"],
                       req.get("init_std", 0.01)).push(
                req["keys"], req["grads"], req.get("lr", 1.0))
            if self.snapshot_dir:
                self._mark_dirty(req["table"])
            self._publish_inval(req["table"], req["keys"])
            if self._exporter is not None:
                self._exporter.note_rows(
                    int(np.asarray(req["keys"]).size))
            return True
        if op == "save":
            tag = self.endpoint.replace(":", "_")
            with self._tables_lock:
                items = list(self.tables.items())
            for name, t in items:
                t.save(f"{req['dirname']}/{name}.{tag}.kv")
            return True
        if op == "size":
            t = self.tables.get(req["table"])
            return 0 if t is None else t.size()
        if op == "push_sync":
            self._sync_state(req["trainers"]).push(
                (req["table"], req["dim"], req["keys"], req["grads"],
                 req.get("lr", 1.0)))
            return True
        if op == "send_barrier":
            def apply_fn(pending):
                n = max(int(req["trainers"]), 1)
                for table, dim, keys, grads, lr in pending:
                    # mean over trainers: matches the single-process
                    # full-batch step when each trainer computes the mean
                    # loss of its batch shard
                    t = self.table(table, dim)
                    t.push(keys, grads, lr / n)
                    self._publish_inval(table, keys)
                    if self.snapshot_dir:
                        # sync-mode mutation: the post-barrier delta
                        # snapshot must carry these tables too
                        self._mark_dirty(table)
                    if self._wal is not None:
                        # rows-only record; the barrier's own journal
                        # mark (separate record) only preserves its
                        # reply. A crash between the two is still
                        # exactly-once: a retried barrier re-applies
                        # the VOLATILE pending buffer, which is empty
                        # after a restart because every acked
                        # push_sync dedups via its own mark (and an
                        # unacked one re-buffers exactly once).
                        ks = np.asarray(keys, np.int64).ravel()
                        self._wal_guard(
                            lambda ks=ks, t=t: self._wal.append_rows(
                                table, ks, t.rows_for(ks), dim=t.dim,
                                init_std=t.init_std, seed=t.seed))
            return self._sync_state(req["trainers"]).send_barrier(
                req["worker"], apply_fn)
        if op == "fetch_barrier":
            return self._sync_state(req["trainers"]).fetch_barrier(
                req["worker"])
        if op == "subscribe_inval":
            return self._subscribe_inval()
        if op == "ping":
            return "pong"
        if op == "metrics":
            # Prometheus exposition over this shard process's registry
            # (rpc counters, snapshot costs, table sizes are all here) —
            # the PS scrape point (docs/OBSERVABILITY.md)
            return _obs.prometheus_text()
        if op == "debug_dump":
            # full postmortem bundle (docs/DEBUGGING.md): same handler
            # as the serving frontend, persisted server-side when a
            # debug dir is configured and returned over the wire
            return _debug.dump_verb(req)
        if op == "heartbeat":
            import time
            with self._beats_lock:
                self._beats[int(req["worker"])] = time.time()
            return True
        if op == "lost_workers":
            return self.lost_workers()
        if op == "dgc_push":
            # sparse gradient round (DGC transport, reference dgc_op.h +
            # sparse allreduce in operators/collective): accumulate each
            # trainer's top-k (idx, val) pairs; seal when all arrived.
            # Timeouts propagate — serve_connection turns any dispatch
            # exception into an error frame instead of a dead socket
            return self._dgc_round(req["table"], int(req["trainers"])
                                   ).push(int(req["worker"]),
                                          req["idx"], req["val"])
        if op == "dgc_pull":
            return self._dgc_round(req["table"], int(req["trainers"])
                                   ).pull(int(req["worker"]))
        raise ValueError(f"unknown PS op {op!r}")

    def _dgc_round(self, table: str, trainers: int) -> "_DGCRound":
        with self._sync_lock:
            r = self._dgc.get(table)
            if r is None:
                r = self._dgc[table] = _DGCRound(trainers)
            elif r.trainers != trainers:
                if r.pushed or r.pulled:
                    raise RuntimeError(
                        f"dgc trainer count changed mid-round on "
                        f"{table!r} ({r.trainers} -> {trainers})")
                r = self._dgc[table] = _DGCRound(trainers)
            return r

    def _sync_state(self, trainers: int) -> _SyncRound:
        with self._sync_lock:
            if self._sync is None:
                self._sync = _SyncRound(int(trainers))
            elif self._sync.trainers != int(trainers):
                st = self._sync
                with st.cond:
                    idle = not st.pending and not st.send_done and \
                        not st.fetch_done
                if not idle:
                    raise ValueError(
                        f"sync trainer count changed mid-round "
                        f"({st.trainers} -> {trainers}) with buffered "
                        f"state — restart the job cleanly")
                # a new job with a different world size: fresh round state
                self._sync = _SyncRound(int(trainers))
            return self._sync

    def lost_workers(self) -> list[int]:
        import time
        now = time.time()
        with self._beats_lock:  # handler threads insert concurrently
            beats = list(self._beats.items())
        return sorted(w for w, t in beats
                      if now - t > self.worker_timeout)

    def serve_in_thread(self) -> threading.Thread:
        th = threading.Thread(target=self.serve_forever, daemon=True)
        th.start()
        return th


_NOT_PRIMARY_RE = re.compile(
    r"not_primary(?:\s+primary=(\S*))?(?:\s+epoch=(\d+))?")
_STALE_EPOCH_RE = re.compile(r"stale_epoch\s+epoch=(\d+)")


class PSClient:
    """Worker-side stub: key-hash routing across server shards (reference
    ps_dispatcher hash dispatch + Communicator send path), one
    fault-tolerant RpcClient channel per shard (retry with stable
    request ids, per-request deadlines, backoff — reference brpc
    channel timeout_ms/max_retry).

    HA (docs/PS_HA.md): a shard entry may be a ``|``-joined member
    list, ``primary|standby[|standby2]``. Shard routing is unchanged
    (one ACTIVE endpoint per shard); on a dead or demoted active
    member the client probes the group, adopts the live primary with
    the highest epoch, and replays the in-flight call with the SAME
    request id — server dedup makes the retry exactly-once even
    across a failover."""

    # sync-mode barrier (and DGC round) calls legitimately block
    # server-side for up to 300s waiting on straggler trainers — their
    # per-attempt timeout must outlast that
    BARRIER_TIMEOUT = 340.0

    def __init__(self, endpoints: list[str], secret: str | None = None,
                 timeout: float | None = None,
                 deadline: float | None = None,
                 max_retries: int | None = None,
                 backoff: float | None = None):
        self._groups = [str(ep).split("|") for ep in endpoints]
        # active member per shard: shard count and key routing see ONE
        # endpoint per group, exactly the non-HA shape
        self.endpoints = [g[0] for g in self._groups]
        # wire + fault accounting shared across shard channels
        # (bench/diagnostics read .bytes_out/.bytes_in; robustness
        # tests read .stats)
        self.stats = TransportStats()
        self._client_kw = dict(stats=self.stats, secret=secret,
                               timeout=timeout, deadline=deadline,
                               max_retries=max_retries,
                               backoff=backoff)
        self._ha_lock = threading.RLock()
        self._cl_cache: dict[str, RpcClient] = {}
        self._clients = [self._client_for(ep)
                         for ep in self.endpoints]
        self._epochs = [0] * len(self._groups)  # newest epoch seen
        self.failovers = 0        # active-member switches on failure
        self.redirects = 0        # not_primary redirects followed
        self.fenced_rejects = 0   # stale_epoch answers seen
        self._pool = None  # lazy persistent fan-out pool
        self._inval_stop: threading.Event | None = None
        self._inval_threads: list[threading.Thread] = []
        # pulls answered stale-marked by a shard mid-background-replay
        # (read-through gate): values predate WAL catch-up. Count, not
        # content — training tolerates bounded staleness by design
        self.stale_pulls = 0
        self.last_pull_stale = False
        # rows the tiered store demand-paged to answer our pulls
        # (docs/PS_TIERED.md): cost visibility for the cold tier
        self.cold_faults = 0
        self.last_pull_cold_faults = 0

    @property
    def bytes_out(self) -> int:
        return self.stats.bytes_out

    @property
    def bytes_in(self) -> int:
        return self.stats.bytes_in

    def _client_for(self, ep: str) -> RpcClient:
        with self._ha_lock:
            cl = self._cl_cache.get(ep)
            if cl is None:
                cl = self._cl_cache[ep] = RpcClient(
                    ep, **self._client_kw)
            return cl

    def _call(self, i: int, req: dict, **kw):
        if len(self._groups[i]) == 1:
            # non-HA shard: exactly the pre-HA code path
            return self._clients[i].call(req, **kw)
        return self._ha_call(i, req, **kw)

    # -- HA failover path (docs/PS_HA.md) --------------------------------
    def _set_active(self, i: int, ep: str):
        with self._ha_lock:
            if ep not in self._groups[i]:
                self._groups[i].append(ep)
            self.endpoints[i] = ep
            self._clients[i] = self._client_for(ep)

    def _advance(self, i: int):
        with self._ha_lock:
            g = self._groups[i]
            cur = self.endpoints[i]
            j = (g.index(cur) + 1) % len(g) if cur in g else 0
            self._set_active(i, g[j])

    def _failover(self, i: int):
        """Probe the group for a live primary (short single-attempt
        ha_status calls) and adopt the one with the highest epoch;
        with none answering yet (promotion in flight) stay put — the
        caller's retry loop keeps probing until its deadline."""
        with self._ha_lock:
            group = list(self._groups[i])
            cur = self.endpoints[i]
        best_ep, best_epoch = None, -1
        for ep in group:
            if ep == cur:
                continue
            try:
                st = self._client_for(ep).call(
                    {"op": "ha_status"}, timeout=1.0, deadline=1.5,
                    max_retries=0)
            except Exception:
                continue
            if isinstance(st, dict) and st.get("role") == "primary" \
                    and not st.get("fenced"):
                e = int(st.get("epoch", 0))
                if e > best_epoch:
                    best_ep, best_epoch = ep, e
        with self._ha_lock:
            if best_ep is not None \
                    and best_epoch >= self._epochs[i]:
                self._epochs[i] = max(self._epochs[i], best_epoch)
                self._set_active(i, best_ep)
                self.failovers += 1
                _flight.record("ps_client", "ha_failover", shard=i,
                               endpoint=best_ep, epoch=best_epoch)
                return True
        return False

    def _ha_call(self, i: int, req: dict, timeout: float | None = None,
                 deadline: float | None = None, req_id=None, **kw):
        """Group-aware call: pin the request id up front so every
        retry — including against a freshly promoted standby — is the
        SAME request to the dedup cache; follow not_primary redirects;
        adopt newer epochs from stale_epoch answers; probe the group
        on transport failures. Bounded by the normal call deadline."""
        cl0 = self._clients[i]
        budget = deadline if deadline is not None else cl0.deadline
        deadline_ts = time.monotonic() + budget
        if req_id is None:
            req_id = cl0._next_id()
        barrier = req.get("op") in ("send_barrier", "fetch_barrier",
                                    "dgc_push", "dgc_pull")
        probe = float(os.environ.get(
            "PADDLE_PS_HA_PROBE", "2.0") or 2.0)
        last: Exception | None = None
        while True:
            with self._ha_lock:
                cl = self._clients[i]
                epoch = self._epochs[i]
            r = dict(req)
            if epoch:
                r["_epoch"] = epoch
            left = deadline_ts - time.monotonic()
            if left <= 0:
                raise PSDeadlineError(
                    f"PS {req.get('op')!r} failed across HA group "
                    f"{self._groups[i]}: {last}") from last
            if barrier:
                # barrier dispatch legitimately blocks on stragglers:
                # a short probing cycle would tear rounds apart
                cycle = min(left, (timeout or self.BARRIER_TIMEOUT)
                            + 5.0)
            else:
                cycle = min(left, max(probe, 0.2))
            try:
                if not barrier and "max_retries" not in kw:
                    # single attempt per cycle: the OUTER loop owns
                    # retries here, so a dead active member triggers a
                    # group probe NOW instead of burning the whole
                    # probe cycle in reconnect backoff against it
                    return cl.call(r, timeout=timeout, deadline=cycle,
                                   req_id=req_id, max_retries=0, **kw)
                return cl.call(r, timeout=timeout, deadline=cycle,
                               req_id=req_id, **kw)
            except PSRemoteError as e:
                msg = str(e)
                m = _NOT_PRIMARY_RE.search(msg)
                if m is not None:
                    self.redirects += 1
                    last = e
                    with self._ha_lock:
                        if int(m.group(2) or 0) > self._epochs[i]:
                            self._epochs[i] = int(m.group(2) or 0)
                    if m.group(1):
                        self._set_active(i, m.group(1))
                    else:
                        self._failover(i) or self._advance(i)
                    continue
                m = _STALE_EPOCH_RE.search(msg)
                if m is None:
                    raise
                self.fenced_rejects += 1
                last = e
                srv_epoch = int(m.group(1))
                with self._ha_lock:
                    behind = srv_epoch > self._epochs[i]
                    if behind:
                        # we were behind this server: adopt its epoch
                        # and retry it
                        self._epochs[i] = srv_epoch
                if not behind:
                    # the server is the stale one (zombie): find the
                    # successor primary
                    self._failover(i) or self._advance(i)
            except (PSDeadlineError, ConnectionError, OSError) as e:
                last = e
                if self._failover(i):
                    continue    # adopted a live primary: retry NOW
            time.sleep(0.05)

    def _route(self, keys: np.ndarray) -> np.ndarray:
        return (keys.astype(np.int64) % len(self.endpoints)).astype(np.int64)

    def _fanout(self, calls):
        """Dispatch shard RPCs concurrently over a persistent pool
        (reference Communicator's long-lived send threads); sequential
        round-trips would make latency N_shards x RTT."""
        if len(calls) <= 1:
            return [fn() for fn in calls]
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=len(self.endpoints),
                thread_name_prefix="ps-client")
        return list(self._pool.map(lambda fn: fn(), calls))

    def pull(self, table: str, dim: int, keys,
             init_std: float = 0.01) -> np.ndarray:
        keys = np.asarray(keys, np.int64).ravel()
        owner = self._route(keys)
        out = np.empty((len(keys), dim), np.float32)
        masks = [(i, owner == i) for i in range(len(self.endpoints))]
        masks = [(i, m) for i, m in masks if m.any()]
        res = self._fanout([
            (lambda i=i, m=m: self._call(i, {"op": "pull", "table": table,
                                             "dim": dim,
                                             "keys": keys[m],
                                             "init_std": init_std}))
            for i, m in masks])
        stale = False
        cold = 0
        for (i, m), r in zip(masks, res):
            if isinstance(r, dict):  # replay-gate / tiered-store reply
                stale = stale or bool(r.get("stale"))
                cold += int(r.get("cold_faults", 0))
                r = r["v"]
            out[m] = r
        if stale:
            self.stale_pulls += 1
        self.last_pull_stale = stale
        self.cold_faults += cold
        self.last_pull_cold_faults = cold
        return out

    def push(self, table: str, dim: int, keys, grads, lr: float = 1.0,
             sync: bool = False, trainers: int = 1,
             init_std: float = 0.01):
        keys = np.asarray(keys, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(keys), dim)
        owner = self._route(keys)
        op = "push_sync" if sync else "push"
        masks = [(i, owner == i) for i in range(len(self.endpoints))]
        self._fanout([
            (lambda i=i, m=m: self._call(i, {"op": op, "table": table,
                                             "dim": dim, "keys": keys[m],
                                             "grads": grads[m],
                                             "lr": lr,
                                             "trainers": trainers,
                                             "init_std": init_std}))
            for i, m in masks if m.any()])

    def send_barrier(self, worker: int, trainers: int):
        """Block until every trainer finished this round's pushes; the
        last arrival applies the buffered round (reference
        send_barrier round semantics)."""
        self._fanout([
            (lambda i=i: self._call(i, {"op": "send_barrier",
                                        "worker": worker,
                                        "trainers": trainers},
                                    timeout=self.BARRIER_TIMEOUT))
            for i in range(len(self.endpoints))])

    def fetch_barrier(self, worker: int, trainers: int):
        """Block until every trainer pulled the freshly applied params."""
        self._fanout([
            (lambda i=i: self._call(i, {"op": "fetch_barrier",
                                        "worker": worker,
                                        "trainers": trainers},
                                    timeout=self.BARRIER_TIMEOUT))
            for i in range(len(self.endpoints))])

    def size(self, table: str) -> int:
        return sum(self._call(i, {"op": "size", "table": table})
                   for i in range(len(self.endpoints)))

    def heartbeat(self, worker_id: int):
        """Liveness ping to every shard (reference HeartBeatMonitor's
        worker-side UPDATE)."""
        self._fanout([
            (lambda i=i: self._call(i, {"op": "heartbeat",
                                        "worker": worker_id}))
            for i in range(len(self.endpoints))])

    def lost_workers(self) -> list[int]:
        lost: set[int] = set()
        for i in range(len(self.endpoints)):
            lost.update(self._call(i, {"op": "lost_workers"}))
        return sorted(lost)

    def save(self, dirname: str):
        for i in range(len(self.endpoints)):
            self._call(i, {"op": "save", "dirname": dirname})

    def metrics(self, shard: int | None = None):
        """Prometheus text from one shard (or every shard when None) —
        scrape helper for the PS `metrics` verb."""
        if shard is not None:
            return self._call(shard, {"op": "metrics"})
        return {ep: self._call(i, {"op": "metrics"})
                for i, ep in enumerate(self.endpoints)}

    def debug_dump(self, shard: int | None = None,
                   write: bool = True):
        """Postmortem bundle from one shard (or every shard when None)
        — metrics, trace ring, flight rings, env. `write=True` also
        persists it shard-side into the shard's own
        PADDLE_TPU_DEBUG_DIR (the destination is never
        wire-controlled; docs/DEBUGGING.md)."""
        req = {"op": "debug_dump", "write": bool(write)}
        if shard is not None:
            return self._call(shard, dict(req))
        return {ep: self._call(i, dict(req))
                for i, ep in enumerate(self.endpoints)}

    # -- hot-row invalidation subscription (PR 11) -----------------------
    def subscribe_invalidations(self, callback) -> threading.Event:
        """Subscribe to every shard's push-invalidation stream over the
        multiplexed channel (the stream shares the shard channel with
        pulls/pushes — no extra connection). ``callback(table, keys)``
        fires per event from a background thread; ``keys`` is an int64
        array, or ``None`` for a whole-table invalidation (the server
        overflowed this subscriber's queue). Returns a stop Event —
        set it (or call ``close()``) to end the subscription; each
        stream's F_CANCEL then frees the server-side subscriber.

        Reconnect loop: a dead shard ends the stream with a transport
        error; the thread re-subscribes with backoff, and the FIRST
        event after a resubscribe is preceded by a synthetic
        whole-table pass only if the server reports overflow — a
        subscriber that missed pushes while disconnected should treat
        the resubscribe ack as a full-invalidation trigger itself via
        ``on_resubscribe``-style wrapping if it needs that guarantee
        (BoxPSWrapper.flush's refresh covers the training loop)."""
        if self._inval_stop is not None and not self._inval_stop.is_set():
            raise RuntimeError("invalidation subscription already active")
        stop = threading.Event()
        self._inval_stop = stop
        self._inval_threads = [
            threading.Thread(target=self._inval_loop,
                             args=(i, callback, stop), daemon=True,
                             name=f"ps-inval-{i}")
            for i in range(len(self.endpoints))]
        for th in self._inval_threads:
            th.start()
        return stop

    def _inval_loop(self, i: int, callback, stop: threading.Event):
        while not stop.is_set():
            gen = None
            try:
                gen = self._clients[i].call_stream(
                    {"op": "subscribe_inval"},
                    timeout=30.0, stream_timeout=30.0)
                for ev in gen:
                    if stop.is_set():
                        return
                    if not isinstance(ev, dict):
                        continue
                    table = ev.get("table")
                    if table is None:   # subscribed/keepalive frames
                        continue
                    if ev.get("full"):
                        callback(table, None)
                    else:
                        callback(table,
                                 np.asarray(ev["keys"], np.int64))
            except Exception:
                pass   # shard down or stream stalled: resubscribe
            finally:
                if gen is not None:
                    try:
                        gen.close()   # sends F_CANCEL if mid-stream
                    except Exception:
                        pass
            stop.wait(0.5)

    # -- DGC sparse-gradient rounds (shard by index hash) ----------------
    def dgc_allreduce(self, name: str, idx, val, worker: int,
                      trainers: int):
        """Exchange top-k sparse gradients: push this worker's (idx,
        val), receive the all-trainer merged sparse gradient. Wire cost
        is O(k) both ways vs O(N) for a dense exchange — this is the
        DGC transport the dgc_momentum op's compression exists for."""
        idx = np.asarray(idx, np.int64).ravel()
        val = np.asarray(val, np.float32).ravel()
        owner = self._route(idx)
        calls = []
        for i in range(len(self.endpoints)):
            m = owner == i
            calls.append((lambda i=i, m=m: self._call(
                i, {"op": "dgc_push", "table": name, "idx": idx[m],
                    "val": val[m], "worker": worker,
                    "trainers": trainers},
                timeout=self.BARRIER_TIMEOUT)))
        # round failures (straggler timeout, trainer-count change)
        # surface as PSRemoteError from the error frame
        self._fanout(calls)
        parts = self._fanout([
            (lambda i=i: self._call(i, {"op": "dgc_pull", "table": name,
                                        "worker": worker,
                                        "trainers": trainers},
                                    timeout=self.BARRIER_TIMEOUT))
            for i in range(len(self.endpoints))])
        midx = np.concatenate([p["idx"] for p in parts])
        mval = np.concatenate([p["val"] for p in parts])
        order = np.argsort(midx, kind="stable")
        return midx[order], mval[order]

    def close(self):
        if self._inval_stop is not None:
            self._inval_stop.set()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        with self._ha_lock:
            clients = list(self._cl_cache.values())
            self._cl_cache.clear()
        for c in clients:
            c.close()


class ParameterServerRuntime:
    """fleet runtime: the server role owns a PSServer shard; the worker
    role owns a PSClient over all server endpoints (reference
    runtime/parameter_server_runtime.py lifecycle)."""

    def __init__(self, role_maker):
        self._role_maker = role_maker
        self.server: PSServer | None = None
        self.client: PSClient | None = None
        self._thread: threading.Thread | None = None

    # -- server lifecycle ----------------------------------------------
    def init_server(self, *args, **kwargs):
        eps = self._role_maker.get_pserver_endpoints()
        me = eps[self._role_maker.server_index()]
        if "|" in me:
            # HA group entry (docs/PS_HA.md): bind the member matching
            # this process's identity; primary/standby role comes from
            # PADDLE_PS_HA_PRIMARY (the launcher sets both)
            members = me.split("|")
            mine = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
            me = mine if mine in members else members[0]
        self.server = PSServer(me)
        model_dir = args[0] if args else kwargs.get("dirname")
        if model_dir:
            import glob
            import os
            tag = self.server.endpoint.replace(":", "_")
            for path in glob.glob(f"{model_dir}/*.{tag}.kv"):
                name = os.path.basename(path).split(".")[0]
                t = LargeScaleKV(1)
                t.load(path)
                self.server.tables[name] = t

    def run_server(self, block: bool = False):
        if self.server is None:
            self.init_server()
        if block:
            self.server.serve_forever()
        else:
            self._thread = self.server.serve_in_thread()
        return self.server

    def stop_server(self):
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()

    # -- worker lifecycle ----------------------------------------------
    def init_worker(self):
        self.client = PSClient(self._role_maker.get_pserver_endpoints())
        return self.client

    def stop_worker(self):
        if self.client is not None:
            self.client.close()

    def get_table(self, name: str, dim: int) -> LargeScaleKV:
        """In-process access (single-process/local mode) — no socket."""
        if self.server is not None:
            return self.server.table(name, dim)
        if not hasattr(self, "_local_tables"):
            self._local_tables: dict[str, LargeScaleKV] = {}
        if name not in self._local_tables:
            self._local_tables[name] = LargeScaleKV(dim)
        return self._local_tables[name]
