"""FleetWrapper facade + Downpour async worker over the PS/KV tier.

Reference counterparts:
  framework/fleet/fleet_wrapper.h:60  — PullSparseVarsSync /
    PushSparseVarsWithLabelAsync / PullDenseVarsSync / PushDenseVarsAsync
    / SaveModel / LoadModel over pslib
  framework/device_worker.h:246       — DownpourWorker: per-thread loop
    pulling the batch's sparse rows, computing fwd/bwd, pushing grads
    asynchronously while other threads keep training

TPU stance (SURVEY §7): embedding tables that fit HBM use the
mesh-sharded design (parallel/embedding.py); this tier serves the
beyond-HBM PaddleRec regime. The worker's local step IS a jax program
(fwd+bwd jitted); only pulls/pushes run host-side against the TCP
PSClient (or in-process LargeScaleKV for local mode) — the reference's
pslib RPC layer replaced by the KV arena in native/kv_store.cc, over
the fault-tolerant transport in runtime/rpc.py (client retries with
stable request ids; the server dedups, so a retried push applies
exactly once).
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from .runtime.parameter_server_runtime import LargeScaleKV, PSClient

__all__ = ["FleetWrapper", "DownpourWorker"]


class FleetWrapper:
    """pull/push sparse + dense, save/load — the fleet_wrapper.h surface
    over PSClient (distributed) or in-process tables (local mode)."""

    def __init__(self, endpoints=None):
        self._client = PSClient(list(endpoints)) if endpoints else None
        self._local: dict[str, LargeScaleKV] = {}
        self.scale_sparse_gradient_with_batch_size = True

    @classmethod
    def from_role_maker(cls, role_maker):
        return cls(role_maker.get_pserver_endpoints())

    # -- sparse ---------------------------------------------------------
    def _table(self, name: str, dim: int,
               init_std: float = 0.01) -> LargeScaleKV:
        if name not in self._local:
            self._local[name] = LargeScaleKV(dim, init_std=init_std)
        return self._local[name]

    def pull_sparse(self, table: str, ids, dim: int,
                    init_std: float = 0.01) -> np.ndarray:
        """ids [N] -> rows [N, dim] (creating untouched rows with the
        table's initializer — large_scale_kv init-on-first-touch)."""
        ids = np.asarray(ids, np.int64).ravel()
        if self._client is not None:
            return self._client.pull(table, dim, ids, init_std=init_std)
        return self._table(table, dim, init_std).pull(ids)

    def push_sparse(self, table: str, ids, grads, dim: int,
                    lr: float = 1.0, init_std: float = 0.01):
        """Async apply-on-arrival: server does rows -= lr * grads
        (duplicate ids accumulate, reference PushSparseVarsAsync)."""
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), dim)
        if self._client is not None:
            self._client.push(table, dim, ids, grads, lr,
                              init_std=init_std)
        else:
            self._table(table, dim, init_std).push(ids, grads, lr)

    # -- dense ----------------------------------------------------------
    # a dense param is a KV table keyed 0..rows-1 with ZERO init (the
    # worker seeds the real init once via push_initial_dense)
    def pull_dense(self, name: str, shape) -> np.ndarray:
        shape = tuple(shape)
        m = shape[0] if len(shape) > 1 else 1
        dim = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
        rows = self.pull_sparse(name, np.arange(m), dim, init_std=0.0)
        return rows.reshape(shape)

    def push_dense(self, name: str, grad: np.ndarray, lr: float = 1.0):
        g = np.asarray(grad, np.float32)
        m = g.shape[0] if g.ndim > 1 else 1
        self.push_sparse(name, np.arange(m), g.reshape(m, -1),
                         g.reshape(m, -1).shape[1], lr, init_std=0.0)

    # -- lifecycle ------------------------------------------------------
    def save_model(self, dirname: str, mode=0):
        if self._client is not None:
            self._client.save(dirname)
        else:
            import os
            os.makedirs(dirname, exist_ok=True)
            for name, t in self._local.items():
                t.save(f"{dirname}/{name}.local.kv")

    def load_model(self, dirname: str, mode=0):
        import glob
        import os
        for path in glob.glob(f"{dirname}/*.local.kv"):
            # strip ONLY the fixed suffix: table names may contain dots
            # (dense tables like "mlp0.w")
            name = os.path.basename(path)[:-len(".local.kv")]
            t = LargeScaleKV(1)
            t.load(path)
            self._local[name] = t

    def table_size(self, table: str) -> int:
        if self._client is not None:
            return self._client.size(table)
        t = self._local.get(table)
        return 0 if t is None else t.size()

    def transport_stats(self) -> dict:
        """Retry/timeout/reconnect counters from the PS transport
        (empty in local mode) — the robustness tests and benchmarks
        assert against these."""
        return self._client.stats.as_dict() \
            if self._client is not None else {}

    def stop(self):
        if self._client is not None:
            self._client.close()


class DownpourWorker:
    """Async multi-thread worker loop for wide&deep-style CTR jobs
    (reference DownpourWorker::TrainFiles): each thread pulls the batch's
    touched sparse rows, runs the jitted local fwd+bwd, and pushes grads
    back (server applies on arrival — Downpour/async-SGD semantics).

    The local step reuses models/wide_deep.py's functional core: the
    pulled unique-row matrices stand in for the full tables and the ids
    are remapped onto them, so the exact same forward serves PS mode and
    the mesh-sharded mode."""

    def __init__(self, fleet_wrapper: FleetWrapper, cfg, lr: float = 1e-2,
                 seed: int = 0):
        import jax

        from ...models.wide_deep import widedeep_loss
        self.fw = fleet_wrapper
        self.cfg = cfg
        self.lr = lr
        self._mlp_shapes = None
        self._lock = threading.Lock()
        self._steps = 0
        self._losses: list[float] = []

        def local_loss(params, ids_local, dense, label):
            return widedeep_loss(params, ids_local, dense, label, cfg)

        self._grad_fn = jax.jit(jax.value_and_grad(local_loss))
        # dense-side init pushed once from a seeded init so every worker
        # and the server agree (reference InitServer dense push)
        from ...models.wide_deep import init_widedeep_params
        ref = init_widedeep_params(cfg, seed)
        self._dense_names = ["wide_dense", "bias"] + \
            [f"mlp{i}.{k}" for i in range(len(ref["mlp"]))
             for k in ("w", "b")]
        self._ref = ref

    def _dense_params(self):
        p = {"wide_dense": self.fw.pull_dense(
                 "wide_dense", self._ref["wide_dense"].shape),
             "bias": self.fw.pull_dense("bias", self._ref["bias"].shape),
             "mlp": []}
        for i, layer in enumerate(self._ref["mlp"]):
            p["mlp"].append(
                {"w": self.fw.pull_dense(f"mlp{i}.w", layer["w"].shape),
                 "b": self.fw.pull_dense(f"mlp{i}.b", layer["b"].shape)})
        return p

    def push_initial_dense(self):
        """Rank-0: seed the server's dense tables with the reference
        init (server rows otherwise start from the KV initializer)."""
        self.fw.push_dense("wide_dense",
                           -self._ref["wide_dense"], lr=1.0)
        self.fw.push_dense("bias", -self._ref["bias"], lr=1.0)
        for i, layer in enumerate(self._ref["mlp"]):
            self.fw.push_dense(f"mlp{i}.w", -layer["w"], lr=1.0)
            self.fw.push_dense(f"mlp{i}.b", -layer["b"], lr=1.0)

    def train_one_batch(self, ids, dense, label) -> float:
        import jax.numpy as jnp
        cfg = self.cfg
        ids = np.asarray(ids, np.int64)
        B, S = ids.shape
        uids, inv = np.unique(ids.ravel(), return_inverse=True)
        # pad the unique-id set to a power-of-two bucket: the jitted
        # local step is shaped by len(uids), and unpadded it would
        # recompile for every distinct count (pad rows repeat uids[0];
        # nothing indexes them, so their grads are exactly zero)
        bucket = 1 << max(int(np.ceil(np.log2(max(len(uids), 1)))), 3)
        bucket = min(bucket, B * S)
        if bucket > len(uids):
            uids = np.concatenate(
                [uids, np.full(bucket - len(uids), uids[0], np.int64)])
        emb_rows = self.fw.pull_sparse("embed", uids, cfg.embed_dim)
        wide_rows = self.fw.pull_sparse("wide", uids, 1)
        params = self._dense_params()
        params["embed"] = jnp.asarray(emb_rows)
        params["wide"] = jnp.asarray(wide_rows)
        ids_local = inv.reshape(B, S).astype(np.int32)
        loss, g = self._grad_fn(params, jnp.asarray(ids_local),
                                jnp.asarray(dense, np.float32),
                                jnp.asarray(label, np.float32))
        self.fw.push_sparse("embed", uids, np.asarray(g["embed"]),
                            cfg.embed_dim, self.lr)
        self.fw.push_sparse("wide", uids, np.asarray(g["wide"]), 1,
                            self.lr)
        self.fw.push_dense("wide_dense", np.asarray(g["wide_dense"]),
                           self.lr)
        self.fw.push_dense("bias", np.asarray(g["bias"]).reshape(1, -1),
                           self.lr)
        for i, layer in enumerate(g["mlp"]):
            self.fw.push_dense(f"mlp{i}.w", np.asarray(layer["w"]),
                               self.lr)
            self.fw.push_dense(f"mlp{i}.b",
                               np.asarray(layer["b"]).reshape(1, -1),
                               self.lr)
        lv = float(np.asarray(loss))
        with self._lock:
            self._steps += 1
            self._losses.append(lv)
        return lv

    def train_from_dataset(self, batches, thread_num: int = 2):
        """Drain `batches` (iterable of (ids, dense, label)) with
        `thread_num` concurrent worker threads (reference
        trainer_desc thread_num + DownpourWorker::TrainFiles loop)."""
        q: queue.Queue = queue.Queue(maxsize=2 * thread_num)
        stop = object()
        errs: list[BaseException] = []

        def run():
            while True:
                item = q.get()
                if item is stop:
                    return
                try:
                    self.train_one_batch(*item)
                except BaseException as e:  # surfaced to the caller
                    errs.append(e)
                    return

        threads = [threading.Thread(target=run, daemon=True)
                   for _ in range(thread_num)]
        for t in threads:
            t.start()
        for b in batches:
            # bounded queue: if every worker died on an error the
            # producer must stop instead of blocking on q.put forever
            while True:
                if errs and not any(t.is_alive() for t in threads):
                    break
                try:
                    q.put(b, timeout=0.5)
                    break
                except queue.Full:
                    continue
            if errs and not any(t.is_alive() for t in threads):
                break
        for _ in threads:
            while True:
                try:
                    q.put(stop, timeout=0.5)
                    break
                except queue.Full:
                    if not any(t.is_alive() for t in threads):
                        break
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return list(self._losses)
