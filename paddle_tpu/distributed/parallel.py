"""DataParallel for dygraph (reference fluid/dygraph/parallel.py:236).

Gradient sync = eager all_reduce of grads after backward, amortised by fusing
into flat buckets (replacing imperative/all_reduce.cc coalesced NCCL calls).
With one process this is an identity wrapper (the recommended TPU path is the
sharded static executor / fleet collective instead).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..fluid.dygraph.layers import Layer
from .collective import all_reduce, ReduceOp
from .env import get_world_size

__all__ = ["DataParallel", "scale_loss"]


def scale_loss(loss):
    n = get_world_size()
    if n <= 1:
        return loss
    return loss / n


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers
        self._comm_buffer_bytes = int(comm_buffer_size * (1 << 20))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return scale_loss(loss)

    def apply_collective_grads(self):
        """Allreduce grads fused into flat buckets of ~comm_buffer_size MB
        (reference dygraph/parallel.py:449 coalesced allreduce /
        details/fused_all_reduce_op_handle.cc): one collective per bucket
        instead of one per parameter."""
        if get_world_size() <= 1:
            return
        params = [p for p in self._layers.parameters()
                  if p.grad is not None]
        # bucket by dtype, bounded by the buffer budget
        buckets: list[list] = []
        cur, cur_bytes, cur_dtype = [], 0, None
        for p in params:
            g = p.grad._value
            if cur and (g.dtype != cur_dtype or
                        cur_bytes + g.nbytes > self._comm_buffer_bytes):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += g.nbytes
            cur_dtype = g.dtype
        if cur:
            buckets.append(cur)
        from ..fluid.dygraph.varbase import Tensor
        for bucket in buckets:
            grads = [p.grad._value for p in bucket]
            flat = jnp.concatenate([g.reshape(-1) for g in grads])
            red = all_reduce(flat, ReduceOp.SUM)
            red = red._value if hasattr(red, "_value") else red
            off = 0
            for p, g in zip(bucket, grads):
                n = g.size
                p.grad = Tensor(red[off:off + n].reshape(g.shape),
                                stop_gradient=True)
                off += n

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)
