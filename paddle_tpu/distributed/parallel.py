"""DataParallel for dygraph (reference fluid/dygraph/parallel.py:236).

Gradient sync = eager all_reduce of grads after backward, amortised by fusing
into flat buckets (replacing imperative/all_reduce.cc coalesced NCCL calls).
With one process this is an identity wrapper (the recommended TPU path is the
sharded static executor / fleet collective instead).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..fluid.dygraph.layers import Layer
from .collective import all_reduce, ReduceOp
from .env import get_world_size

__all__ = ["DataParallel", "scale_loss"]


def scale_loss(loss):
    n = get_world_size()
    if n <= 1:
        return loss
    return loss / n


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return scale_loss(loss)

    def apply_collective_grads(self):
        if get_world_size() <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                g = all_reduce(p.grad, ReduceOp.SUM)
                p.grad = g if g is not None else p.grad

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)
