"""Process launcher — `python -m paddle_tpu.distributed.launch`.

Reference: python/paddle/distributed/launch.py:59,140,214 (parse ips/ports
-> Cluster/Pod -> start_local_trainers sets PADDLE_* env, spawns children,
watches and tears all down on failure) and fleet/launch.py (fleetrun, adds
--servers/--workers PS mode).  TPU differences: no per-GPU device
assignment — each process drives its local chips; cross-process rendezvous
is jax.distributed's coordinator (PADDLE_COORDINATOR = first trainer
endpoint) instead of the NCCL-id TCP dance.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main", "get_cluster_env"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch multi-process distributed training")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="trainers on this node (default: 1, or inferred "
                        "from --trainer_endpoints)")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated node ips (this launcher starts "
                        "only the local node's processes)")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--trainer_endpoints", type=str, default=None,
                   help="explicit comma-separated endpoints (overrides "
                        "ips/started_port)")
    p.add_argument("--servers", type=str, default="",
                   help="PS mode: comma-separated server endpoints")
    p.add_argument("--workers", type=str, default="",
                   help="PS mode: comma-separated worker endpoints")
    p.add_argument("--serving_replicas", type=str, default="",
                   help="serving mode: comma-separated replica "
                        "endpoints; spawns one child per endpoint with "
                        "PADDLE_TPU_REPLICA_ENDPOINT / "
                        "PADDLE_TPU_REPLICA_ID set (the script builds "
                        "Engine.from_checkpoint + ServingServer on that "
                        "endpoint; tests/fixtures/serving_replica.py is "
                        "the reference). With --max_restarts > 0 a dead "
                        "replica is respawned ALONE — its state lives "
                        "in the engine checkpoint, and the serving "
                        "router fails in-flight requests over to the "
                        "surviving replicas meanwhile (docs/SERVING.md)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: restart the whole job up to N times "
                        "after a crashed or hung rank (children resume "
                        "from their checkpoints)")
    p.add_argument("--heartbeat_timeout", type=float, default=30.0,
                   help="elastic: seconds without a heartbeat before a "
                        "rank counts as hung (ranks opt in via "
                        "distributed.elastic.start_heartbeat)")
    p.add_argument("--step_deadline", type=float, default=0.0,
                   help="elastic: seconds a rank's heartbeat STEP "
                        "counter may freeze (while still beating) "
                        "before it counts as hung — catches wedged "
                        "collectives a live heartbeat thread hides. "
                        "0 disables; ranks report steps via "
                        "distributed.elastic.note_step")
    p.add_argument("--straggler_lag", type=int, default=10,
                   help="elastic: steps behind the fastest rank before "
                        "a slow-but-progressing rank is flagged "
                        "(paddle_tpu_elastic_straggler_ranks metric + "
                        "flight event). Stragglers are NEVER killed")
    p.add_argument("--restart_backoff", type=float, default=1.0,
                   help="elastic: base seconds of exponential backoff "
                        "between whole-job restarts (doubles per "
                        "restart, capped by --restart_backoff_max; "
                        "0 restarts immediately)")
    p.add_argument("--restart_backoff_max", type=float, default=30.0)
    p.add_argument("--crash_loop_window", type=float, default=60.0,
                   help="elastic: sliding window (seconds) for crash-"
                        "loop detection")
    p.add_argument("--crash_loop_threshold", type=int, default=0,
                   help="elastic: give up once this many job failures "
                        "land inside --crash_loop_window even with "
                        "restart budget left, and write a debug "
                        "bundle naming the flapping rank (0 disables)")
    p.add_argument("--exclude_flapping", action="store_true",
                   help="elastic: after a trainer rank fails "
                        "--flap_threshold times, respawn the job at "
                        "world W-1 WITHOUT it (ranks renumber; "
                        "children resume via the cluster-checkpoint "
                        "resize path, docs/ELASTIC.md)")
    p.add_argument("--flap_threshold", type=int, default=2,
                   help="elastic: failures by one rank before "
                        "--exclude_flapping drops it")
    p.add_argument("--cluster_ckpt_dir", type=str, default=None,
                   help="elastic: set PADDLE_TPU_CLUSTER_CKPT_DIR for "
                        "every child — the coordinated cluster-"
                        "checkpoint store (distributed/cluster_ckpt) "
                        "restarts resume from. NEVER cleared between "
                        "restarts (it IS the cross-life state)")
    p.add_argument("--ps_snapshot_dir", type=str, default=None,
                   help="PS mode: server snapshot directory "
                        "(PADDLE_PS_SNAPSHOT_DIR for the children); "
                        "with --max_restarts > 0 a dead server is "
                        "respawned ALONE from its snapshot instead of "
                        "restarting the whole job. The dir is CLEARED "
                        "at every job(-re)start — snapshots are "
                        "intra-job fault tolerance (workers replay "
                        "from scratch on a full restart; resuming "
                        "stale tables would double-apply their "
                        "pushes); use save/load_model for cross-job "
                        "resume. Default: a temp dir when PS-mode "
                        "elastic restarts are enabled")
    p.add_argument("--ps_snapshot_every", type=int, default=1,
                   help="PS mode: snapshot the server tables every N "
                        "applied pushes (PADDLE_PS_SNAPSHOT_EVERY). "
                        "Default 1 = write-through: a respawned server "
                        "loses NO acknowledged push. N>1 trades that "
                        "durability for throughput — a crash can "
                        "silently drop up to N-1 acked pushes on "
                        "respawn (see docs/PS_WIRE_PROTOCOL.md)")
    p.add_argument("--ps_tier_warm_bytes", type=int, default=0,
                   help="PS mode: opt server tables into the tiered "
                        "embedding store (docs/PS_TIERED.md) with "
                        "this warm-tier RAM budget in bytes per table "
                        "(PADDLE_PS_TIER_WARM_BYTES for server/"
                        "standby children; 0 = all-warm tables). "
                        "Cold rows demand-page from a chunk store "
                        "under the snapshot dir (or "
                        "--ps_tier_store_dir)")
    p.add_argument("--ps_tier_store_dir", type=str, default=None,
                   help="PS mode: cold-tier chunk store directory "
                        "(PADDLE_PS_TIER_STORE_DIR). Default: "
                        "<snapshot_dir>/tier_store")
    p.add_argument("--publish_dir", type=str, default=None,
                   help="online learning: set PADDLE_TPU_PUBLISH_DIR "
                        "for PS server and serving-replica children. "
                        "Servers export their tables through the "
                        "publish pipeline on the PADDLE_TPU_PUBLISH_"
                        "EVERY_* cadence; replicas adopt published "
                        "versions via the router's staggered rollout "
                        "(docs/ONLINE_LEARNING.md)")
    p.add_argument("--metrics_dir", type=str, default=None,
                   help="telemetry: set PADDLE_TPU_METRICS_DIR for "
                        "every child so each process dumps its metric "
                        "registry to <dir>/metrics_<host>_<pid>.json "
                        "at exit; aggregate the job with `python -m "
                        "paddle_tpu.observability.registry <dir>` "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--debug_dir", type=str, default=None,
                   help="postmortem: set PADDLE_TPU_DEBUG_DIR for "
                        "every child so each process writes a debug "
                        "bundle (metrics + trace ring + flight "
                        "recorder + in-flight requests, CRC'd "
                        "manifest) on SIGTERM, unhandled exceptions "
                        "and watchdog stalls — including the teardown "
                        "this launcher runs when a rank dies or hangs. "
                        "List/merge a job's bundles with `python -m "
                        "paddle_tpu.observability.registry <dir>` "
                        "(docs/DEBUGGING.md)")
    p.add_argument("--telemetry", type=str, default=None,
                   nargs="?", const="127.0.0.1:8600",
                   metavar="HOST:PORT",
                   help="fleet telemetry: spawn a collector child on "
                        "this endpoint (default 127.0.0.1:8600 when "
                        "the flag is given bare) and set "
                        "PADDLE_TPU_TELEMETRY_COLLECTOR for every "
                        "other child so each process streams spans / "
                        "flight events / metric deltas to it; watch "
                        "live with `python -m "
                        "paddle_tpu.observability.top --collector "
                        "HOST:PORT` (docs/OBSERVABILITY.md)")
    p.add_argument("--tsdb-dir", type=str, default=None,
                   metavar="DIR",
                   help="with --telemetry: durable metric history — "
                        "the collector child persists its TSDB blocks "
                        "here (PADDLE_TPU_TSDB_DIR), so `top history` "
                        "and SLO burn-rate alerts survive collector "
                        "restarts; without it history is memory-only")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_env(rank, endpoints, role="TRAINER", servers="",
                    workers=""):
    """PADDLE_* env for one child (reference launch_utils.py
    start_local_trainers)."""
    env = {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_TRAINERS_NUM": str(len(endpoints)),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_COORDINATOR": endpoints[0],
        "TRAINING_ROLE": role,
        "FLAGS_selected_gpus": "0",
    }
    if servers:
        env["PADDLE_PSERVERS_IP_PORT_LIST"] = servers
    if workers:
        env["PADDLE_WORKERS_IP_PORT_LIST"] = workers
    return env


def _spawn_one(name, env_over, argv, log_dir):
    env = dict(os.environ)
    env.update(env_over)
    if log_dir:
        fh = open(os.path.join(log_dir, f"{name}.log"), "a")
        stdout = stderr = fh
    else:
        fh, stdout, stderr = None, None, None
    return [name, subprocess.Popen(argv, env=env, stdout=stdout,
                                   stderr=stderr), fh]


def _spawn_children(specs, log_dir):
    """specs: list of (name, env_overrides, argv). Returns proc list."""
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    return [_spawn_one(name, env_over, argv, log_dir)
            for name, env_over, argv in specs]


def _build_ha_state(ha_members):
    """Per-HA-shard failover bookkeeping for _watch: current epoch,
    which child name is primary, and every member's endpoint. Only
    shards with standbys participate (single-member shards keep the
    snapshot-respawn path)."""
    ha_state, name_shard = {}, {}
    for i, members in enumerate(ha_members or []):
        if len(members) < 2:
            continue
        st = {"epoch": 1, "primary": f"server.{i}", "members": {}}
        for j, ep in enumerate(members):
            name = f"server.{i}" if j == 0 else f"standby.{i}.{j}"
            st["members"][name] = ep
            name_shard[name] = i
        ha_state[i] = st
    return ha_state, name_shard


def _watch(procs, manager=None, specs=None, log_dir=None,
           rank_names=None, ha_state=None, name_shard=None):
    """Poll children; on failure or a hung heartbeat kill the rest
    (reference launch.py:214 watch + terminate_local_trainers). Returns
    (rc, needs_restart, offender, reason): the elastic loop in
    `launch` respawns when the manager still has restarts left;
    `offender` is the child name that triggered the teardown (crash or
    first hung rank, None otherwise) and `reason` is "crash" | "hang".

    Graceful degradation: when `specs` carries a respawnable child —
    a `server.*` PS shard (restores from its snapshot) or a
    `replica.*` serving replica (rebuilds from its engine checkpoint;
    the router fails its in-flight work over meanwhile) — and the
    manager still has single-child restart budget, ONLY that child is
    respawned instead of the whole job. Step-lag stragglers are
    reported once (stderr + the manager's metrics/flight event), never
    killed."""
    specs = specs or {}
    rank_names = rank_names or {}
    name_shard = name_shard or {}
    slow_reported: set = set()
    ha_handled: set = set()  # dead HA members deliberately left down
    try:
        while True:
            alive = False
            for entry in procs:
                name, p, fh = entry
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    if name in ha_handled:
                        continue
                    spec = specs.get(name)
                    shard = name_shard.get(name)
                    if shard is not None:
                        done = _ha_member_died(
                            entry, rc, ha_state[shard], shard, spec,
                            specs, manager, log_dir, ha_handled)
                        if done:
                            alive = True
                            continue
                        # shard unrecoverable: fall through to teardown
                    if spec is not None and manager is not None \
                            and (name.startswith("server.")
                                 or name.startswith("replica.")
                                 or name == "telemetry") \
                            and manager.should_restart_server():
                        manager.record_server_restart()
                        if name.startswith("server."):
                            what = "it from snapshot"
                        elif name == "telemetry":
                            what = "the stateless collector alone"
                        else:
                            what = "it alone from its engine checkpoint"
                        sys.stderr.write(
                            f"[launch] {name} exited with code {rc}; "
                            f"restarting {what} "
                            f"({manager.server_restart_count}/"
                            f"{manager.max_server_restarts})\n")
                        if fh:
                            fh.close()
                        entry[:] = _spawn_one(name, spec[0], spec[1],
                                              log_dir)
                        alive = True
                        continue
                    sys.stderr.write(
                        f"[launch] {name} exited with code {rc}; "
                        f"terminating the job\n")
                    _kill_all(procs)
                    return rc, True, name, "crash"
            if not alive:
                return 0, False, None, None
            # PS mode: servers run forever — the job is DONE when every
            # worker/trainer child finished cleanly (reference fleetrun
            # tears servers down once trainers exit)
            worker_rcs = [p.poll() for name, p, _ in procs
                          if not name.startswith("server.")
                          and not name.startswith("standby.")
                          and not name.startswith("replica.")
                          and name != "telemetry"]
            if worker_rcs and all(rc == 0 for rc in worker_rcs) \
                    and any(name.startswith("server.")
                            or name.startswith("standby.")
                            or name == "telemetry"
                            for name, _, _ in procs):
                sys.stderr.write(
                    "[launch] all workers finished; stopping daemon "
                    "children (PS servers / telemetry)\n")
                _kill_all(procs)
                return 0, False, None, None
            if manager is not None:
                hung = manager.hung_ranks()
                if hung:
                    sys.stderr.write(
                        f"[launch] ranks {hung} missed heartbeats for "
                        f">{manager.heartbeat_timeout}s; terminating the "
                        f"job\n")
                    _kill_all(procs)
                    return 1, True, \
                        rank_names.get(hung[0], f"rank{hung[0]}"), \
                        "hang"
                for r in manager.stragglers():
                    if r not in slow_reported:
                        slow_reported.add(r)
                        sys.stderr.write(
                            f"[launch] rank {r} lags "
                            f">{manager.straggler_lag} steps behind "
                            f"the fastest rank (straggler — flagged, "
                            f"not killed)\n")
            time.sleep(0.2)
    except KeyboardInterrupt:
        _kill_all(procs)
        return 1, False, None, None
    finally:
        for _, _, fh in procs:
            if fh:
                fh.close()


def _ha_member_died(entry, rc, st, shard, spec, specs, manager,
                    log_dir, ha_handled):
    """One member of an HA PS shard exited. A dead PRIMARY is fenced
    out by promoting the most-caught-up live standby with a bumped
    epoch — failover costs no restart budget and no snapshot replay.
    The dead member is then respawned as a fresh standby of the
    current primary (budget-counted); with no budget left the shard
    keeps running on its survivors. Returns True when the shard is
    still served (the caller keeps watching), False when it is lost
    (no live member, no respawn budget) and the job must tear down."""
    name = entry[0]
    if name == st["primary"]:
        from .fleet.runtime.ps_ha import promote_best
        others = [ep for n, ep in st["members"].items() if n != name]
        promoted = promote_best(others, st["epoch"] + 1)
        if promoted is not None:
            st["epoch"] += 1
            st["primary"] = next(n for n, ep in st["members"].items()
                                 if ep == promoted)
            sys.stderr.write(
                f"[launch] {name} (PS shard {shard} primary) exited "
                f"with code {rc}; promoting standby {promoted} "
                f"(epoch {st['epoch']})\n")
    shard_alive = st["primary"] != name
    if spec is not None and manager is not None \
            and manager.should_restart_server():
        manager.record_server_restart()
        env2 = dict(spec[0])
        if shard_alive:
            env2["PADDLE_PS_HA_PRIMARY"] = st["members"][st["primary"]]
            env2.pop("PADDLE_PS_HA_EPOCH", None)
            what = (f"respawning it as a standby of "
                    f"{st['members'][st['primary']]}")
        else:
            # no standby answered the promotion probe: bring the dead
            # primary itself back at the current epoch
            env2.pop("PADDLE_PS_HA_PRIMARY", None)
            env2["PADDLE_PS_HA_EPOCH"] = str(st["epoch"])
            what = "restarting it from snapshot"
        sys.stderr.write(
            f"[launch] {name} exited with code {rc}; {what} "
            f"({manager.server_restart_count}/"
            f"{manager.max_server_restarts})\n")
        specs[name] = (env2, spec[1])
        if entry[2]:
            entry[2].close()
        entry[:] = _spawn_one(name, env2, spec[1], log_dir)
        return True
    if shard_alive:
        # no respawn budget, but a promoted/live member carries the
        # shard — leave this member down and keep the job running
        ha_handled.add(name)
        sys.stderr.write(
            f"[launch] {name} exited with code {rc}; shard {shard} "
            f"continues on {st['members'][st['primary']]} "
            f"(no respawn budget left)\n")
        return True
    return False


def _kill_all(procs):
    for _, p, _ in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + 5
    for _, p, _ in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if p.poll() is None:
            p.kill()


def launch(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    script = [sys.executable, args.training_script] \
        + args.training_script_args
    specs = []
    ha_members: list[list[str]] = []
    if args.servers or args.workers:
        # PS mode (fleetrun --servers/--workers). A server entry may be
        # a |-joined HA group, primary|standby[|standby2] (docs/
        # PS_HA.md): member 0 starts as the shard primary, the rest as
        # hot standbys replicating its WAL. Workers receive the raw
        # group string and route pushes to ONE active member per shard.
        servers = [e for e in args.servers.split(",") if e]
        workers = [e for e in args.workers.split(",") if e]
        ha_members = [s.split("|") for s in servers]
        for i, members in enumerate(ha_members):
            for j, ep in enumerate(members):
                env = get_cluster_env(0, workers or ["127.0.0.1:6170"],
                                      role="PSERVER",
                                      servers=args.servers,
                                      workers=args.workers)
                # a server's identity is its OWN endpoint/index, not
                # worker 0's (the trainer fields above only give
                # servers the cluster layout)
                env.update({"PADDLE_CURRENT_ENDPOINT": ep,
                            "PADDLE_PORT": ep.rsplit(":", 1)[1],
                            "POD_IP": ep.rsplit(":", 1)[0],
                            "PADDLE_SERVER_ID": str(i)})
                if len(members) > 1:
                    # HA shard: replication ships WAL records, so the
                    # row journal is mandatory; the starting primary
                    # opens at epoch 1 so fencing can tell its zombies
                    # from a promoted successor
                    env["PADDLE_PS_WAL"] = "1"
                    if j == 0:
                        env["PADDLE_PS_HA_EPOCH"] = "1"
                    else:
                        env["PADDLE_PS_HA_PRIMARY"] = members[0]
                name = f"server.{i}" if j == 0 \
                    else f"standby.{i}.{j}"
                specs.append((name, env, script))
        for i, ep in enumerate(workers):
            env = get_cluster_env(i, workers, role="TRAINER",
                                  servers=args.servers,
                                  workers=args.workers)
            specs.append((f"worker.{i}", env, script))
    elif args.serving_replicas:
        # serving fleet: one replica child per endpoint, identity via
        # env (the script builds Engine.from_checkpoint + ServingServer
        # on PADDLE_TPU_REPLICA_ENDPOINT); the router process is the
        # operator's own (paddle_tpu.serving.Router)
        for i, ep in enumerate(e for e in args.serving_replicas.split(",")
                               if e):
            specs.append((f"replica.{i}",
                          {"PADDLE_TPU_REPLICA_ENDPOINT": ep,
                           "PADDLE_TPU_REPLICA_ID": str(i)}, script))
    else:
        if args.trainer_endpoints:
            endpoints = args.trainer_endpoints.split(",")
        else:
            n = args.nproc_per_node or 1
            ips = args.ips.split(",")
            endpoints = [f"{ip}:{args.started_port + i}"
                         for ip in ips for i in range(n)]
        my_ip = args.ips.split(",")[args.node_rank]
        n_local = args.nproc_per_node or \
            len([e for e in endpoints if e.startswith(my_ip + ":")])
        if n_local == 0:
            sys.stderr.write(
                f"[launch] no endpoints on this node ({my_ip}) — pass "
                f"--nproc_per_node or include this node's ip in "
                f"--trainer_endpoints/--ips\n")
            return 1
        base = args.node_rank * n_local
        for i in range(n_local):
            rank = base + i
            specs.append((f"trainer.{rank}",
                          get_cluster_env(rank, endpoints), script))
    if args.metrics_dir:
        os.makedirs(args.metrics_dir, exist_ok=True)
        for _name, env, _argv in specs:
            env["PADDLE_TPU_METRICS_DIR"] = args.metrics_dir
    if args.debug_dir:
        os.makedirs(args.debug_dir, exist_ok=True)
        for _name, env, _argv in specs:
            env["PADDLE_TPU_DEBUG_DIR"] = args.debug_dir
    if args.cluster_ckpt_dir:
        os.makedirs(args.cluster_ckpt_dir, exist_ok=True)
        for _name, env, _argv in specs:
            env["PADDLE_TPU_CLUSTER_CKPT_DIR"] = args.cluster_ckpt_dir
    if args.publish_dir:
        # online learning: servers PUBLISH through this store, serving
        # replicas ADOPT from it (workers/trainers don't need it)
        os.makedirs(args.publish_dir, exist_ok=True)
        for name, env, _argv in specs:
            if name.startswith(("server.", "replica.")):
                env["PADDLE_TPU_PUBLISH_DIR"] = args.publish_dir
    if args.telemetry:
        # fleet telemetry: one collector child answers the tel_* verbs;
        # every rank's agent autostarts from this env at observability
        # import and streams spans/flight/metric deltas to it. Agents
        # reconnect with backoff, so neither spawn order nor collector
        # respawns matter to serving.
        for name, env, _argv in specs:
            env["PADDLE_TPU_TELEMETRY_COLLECTOR"] = args.telemetry
            env.setdefault("PADDLE_TPU_TELEMETRY_ROLE", name)
        tel_env = {"PADDLE_TPU_TELEMETRY_COLLECTOR": ""}
        if args.tsdb_dir:
            os.makedirs(args.tsdb_dir, exist_ok=True)
            tel_env["PADDLE_TPU_TSDB_DIR"] = args.tsdb_dir
        specs.append(("telemetry", tel_env,
                      [sys.executable, "-m",
                       "paddle_tpu.observability.collector",
                       "--endpoint", args.telemetry]))
    from .elastic import ElasticManager
    hb_dir = None
    if args.max_restarts > 0:
        import tempfile
        hb_dir = tempfile.mkdtemp(prefix="paddle_elastic_hb_")
        for _name, env, _argv in specs:
            env["PADDLE_ELASTIC_HEARTBEAT_DIR"] = hb_dir
    ps_mode = bool(args.servers or args.workers)
    has_standbys = any(len(m) > 1 for m in ha_members)
    snap_dir = args.ps_snapshot_dir
    if ps_mode and (args.max_restarts > 0 or has_standbys) \
            and snap_dir is None:
        # HA standbys need the WAL tier (replication ships journal
        # records), and the WAL needs a snapshot dir for its bases
        import tempfile
        snap_dir = tempfile.mkdtemp(prefix="paddle_ps_snap_")
    server_specs = {}
    if snap_dir:
        for name, env, argv in specs:
            if name.startswith(("server.", "standby.")):
                env["PADDLE_PS_SNAPSHOT_DIR"] = snap_dir
                env["PADDLE_PS_SNAPSHOT_EVERY"] = \
                    str(args.ps_snapshot_every)
                server_specs[name] = (env, argv)
    if ps_mode and args.ps_tier_warm_bytes > 0:
        # tiered embedding store (docs/PS_TIERED.md): every server/
        # standby child opts its tables in under the same budget; the
        # cold store defaults under the snapshot dir
        for name, env, argv in specs:
            if name.startswith(("server.", "standby.")):
                env["PADDLE_PS_TIER_WARM_BYTES"] = \
                    str(args.ps_tier_warm_bytes)
                if args.ps_tier_store_dir:
                    env["PADDLE_PS_TIER_STORE_DIR"] = \
                        args.ps_tier_store_dir
    if args.serving_replicas and args.max_restarts > 0:
        # serving replicas respawn ALONE like PS shards: their state is
        # the engine checkpoint the child script restores from, and the
        # router redispatches around the gap
        for name, env, argv in specs:
            if name.startswith("replica."):
                server_specs[name] = (env, argv)
    if args.telemetry and args.max_restarts > 0:
        # the collector is stateless — respawn it alone; agents just
        # reconnect, serving is never in the loop
        for name, env, argv in specs:
            if name == "telemetry":
                server_specs[name] = (env, argv)
    manager = ElasticManager(
        max_restarts=args.max_restarts,
        heartbeat_timeout=args.heartbeat_timeout,
        heartbeat_dir=hb_dir,
        # the telemetry collector never writes heartbeat files — it
        # must not count toward the expected rank set
        world_size=sum(1 for n, _, _ in specs if n != "telemetry"),
        step_deadline=args.step_deadline,
        straggler_lag=args.straggler_lag) \
        if args.max_restarts > 0 else None

    fail_times: list[float] = []     # monotonic stamps of job failures
    offender_counts: dict[str, int] = {}
    server_specs0 = dict(server_specs)  # pristine roles per attempt
    while True:
        if hb_dir:  # fresh heartbeat epoch per attempt
            for f in os.listdir(hb_dir):
                os.unlink(os.path.join(hb_dir, f))
        # whole-job (re)start resets HA roles: member 0 is primary at
        # epoch 1 again (the snapshot dir is cleared below, so there
        # is no prior shard state for a stale epoch to fence)
        server_specs = dict(server_specs0)
        ha_state, name_shard = _build_ha_state(ha_members)
        if snap_dir and os.path.isdir(snap_dir):
            # whole-job (re)start: workers replay from scratch with
            # fresh request ids, so a server resuming mid-run tables
            # from a stale snapshot would double-apply every first-life
            # push — servers must start fresh too. (Single-server
            # respawn inside _watch intentionally KEEPS the snapshot:
            # there the workers' in-flight state continues. The
            # cluster-checkpoint dir is likewise never cleared — it is
            # the state restarts resume from.)
            for f in os.listdir(snap_dir):
                os.unlink(os.path.join(snap_dir, f))
        procs = _spawn_children(specs, args.log_dir)
        # forward SIGTERM to the job
        signal.signal(signal.SIGTERM, lambda *a: (_kill_all(procs),
                                                  sys.exit(143)))
        rc, needs_restart, offender, reason = _watch(
            procs, manager, specs=server_specs, log_dir=args.log_dir,
            rank_names=_heartbeat_rank_names(specs),
            ha_state=ha_state, name_shard=name_shard)
        if rc == 0 or manager is None or not needs_restart:
            return rc
        if offender is not None:
            offender_counts[offender] = \
                offender_counts.get(offender, 0) + 1
        now = time.monotonic()
        fail_times.append(now)
        recent = [t for t in fail_times
                  if now - t <= args.crash_loop_window]
        flapping = max(offender_counts, key=offender_counts.get) \
            if offender_counts else None
        if args.crash_loop_threshold \
                and len(recent) >= args.crash_loop_threshold:
            # crash loop: restarting is burning the budget without
            # progress — stop, leave a postmortem naming the repeat
            # offender
            sys.stderr.write(
                f"[launch] crash loop: {len(recent)} failures within "
                f"{args.crash_loop_window:g}s (flapping: {flapping}); "
                f"giving up\n")
            manager.record_giveup("crash_loop", flapping)
            _write_giveup_bundle(args, "crash_loop", flapping,
                                 offender_counts, manager, rc)
            return rc or 1
        if not manager.should_restart():
            manager.record_giveup("restarts_exhausted", flapping)
            _write_giveup_bundle(args, "restarts_exhausted", flapping,
                                 offender_counts, manager, rc)
            return rc
        manager.record_restart(reason or "crash")
        sys.stderr.write(
            f"[launch] elastic restart "
            f"{manager.restart_count}/{manager.max_restarts}\n")
        if args.exclude_flapping and offender is not None \
                and offender_counts.get(offender, 0) \
                >= args.flap_threshold:
            shrunk = _drop_trainer_rank(specs, offender)
            if shrunk is not None:
                specs = shrunk
                manager.world_size = sum(
                    1 for n, _, _ in specs if n != "telemetry")
                # identities renumbered — restart the flap accounting
                offender_counts.clear()
                sys.stderr.write(
                    f"[launch] excluding flapping rank {offender} "
                    f"(failed {args.flap_threshold}+ times); "
                    f"respawning at world {manager.world_size} — "
                    f"children resume via the cluster-checkpoint "
                    f"resize path\n")
        delay = 0.0
        if args.restart_backoff > 0:
            delay = min(
                args.restart_backoff * 2 ** (manager.restart_count - 1),
                args.restart_backoff_max)
            sys.stderr.write(
                f"[launch] backing off {delay:.1f}s before restart\n")
            time.sleep(delay)
        manager.reset_epoch()


def _heartbeat_rank_names(specs):
    """Heartbeat rank → child name (ranks come from the child's
    PADDLE_TRAINER_ID, which is what start_heartbeat writes)."""
    names = {}
    for name, env, _argv in specs:
        if name == "telemetry":
            continue
        try:
            names[int(env.get("PADDLE_TRAINER_ID", "-1"))] = name
        except ValueError:
            pass
    return names


def _drop_trainer_rank(specs, offender):
    """Rebuild collective-trainer specs at world W-1 without
    ``offender`` (a ``trainer.N`` child name): survivors renumber to
    ranks 0..W-2 and the endpoint list shrinks, so the respawned gang
    forms a valid smaller collective and resumes through
    cluster_ckpt's resize restore. Returns None when not applicable
    (PS/serving modes, unknown name, or nothing would survive)."""
    if not offender.startswith("trainer."):
        return None
    trainers = [(n, e, a) for n, e, a in specs
                if n.startswith("trainer.")]
    others = [s for s in specs if not s[0].startswith("trainer.")]
    keep = sorted((t for t in trainers if t[0] != offender),
                  key=lambda t: int(t[0].split(".", 1)[1]))
    if not keep or len(keep) == len(trainers):
        return None
    endpoints = [t[1]["PADDLE_CURRENT_ENDPOINT"] for t in keep]
    new = []
    for new_rank, (_old, env, argv) in enumerate(keep):
        env = dict(env)
        env.update({
            "PADDLE_TRAINER_ID": str(new_rank),
            "PADDLE_TRAINERS_NUM": str(len(endpoints)),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_COORDINATOR": endpoints[0],
        })
        new.append((f"trainer.{new_rank}", env, argv))
    return new + others


def _write_giveup_bundle(args, reason, flapping, offender_counts,
                         manager, rc):
    """Postmortem for an abandoned job: a PR-5 debug bundle whose
    manifest reason names the flapping rank (best-effort — only when
    a debug dir is configured)."""
    dir_ = args.debug_dir or os.environ.get("PADDLE_TPU_DEBUG_DIR")
    if not dir_:
        return
    try:
        from ..observability import debug as _debug
        tag = f"{reason}:{flapping}" if flapping else reason
        path = _debug.write_bundle(
            dir_, reason=tag,
            extra={"flapping": flapping,
                   "offender_counts": dict(offender_counts),
                   "restarts": manager.restart_count,
                   "exit_code": rc})
        sys.stderr.write(f"[launch] wrote debug bundle {path}\n")
    except Exception as e:  # never mask the real exit path
        sys.stderr.write(f"[launch] debug bundle failed: {e}\n")


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
