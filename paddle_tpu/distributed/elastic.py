"""Elastic training: heartbeats, hang detection, job-level restart.

Reference: python/paddle/distributed/fleet/elastic/* (ElasticManager
watching etcd heartbeats, restarting the pod on scale events or dead
nodes). TPU build: no etcd — heartbeats are mtime-touched files in a
shared directory (PADDLE_ELASTIC_HEARTBEAT_DIR), the launcher's watchdog
(distributed/launch.py --max_restarts) is the manager: a crashed or hung
rank tears the whole job down and respawns it; training scripts resume
from their latest checkpoint (incubate/checkpoint.py TrainEpochRange),
which is exactly the reference's pod-restart recovery contract — XLA
collectives cannot re-admit a single lost rank mid-step any more than
NCCL could.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["HeartbeatWriter", "start_heartbeat", "stale_ranks",
           "ElasticManager"]

_HB_SUFFIX = ".hb"


def _hb_path(dir_, rank):
    return os.path.join(dir_, f"rank{rank}{_HB_SUFFIX}")


class HeartbeatWriter:
    """Touches this rank's heartbeat file every `interval` seconds from a
    daemon thread. The launcher treats a file older than its timeout as a
    hung rank."""

    def __init__(self, dir_: str, rank: int, interval: float = 1.0):
        self.path = _hb_path(dir_, rank)
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None
        self._start_ts = None

    def start(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._start_ts = time.time()
        self._touch()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _touch(self):
        # "start now" content lets stale_ranks compute the job's age
        # (the startup grace window for ranks that haven't opted in
        # yet). Write-then-rename: a truncate-in-place write could be
        # torn by a concurrent stale_ranks read into a garbage
        # start_ts that ends the grace window early
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{self._start_ts} {time.time()}")
        os.replace(tmp, self.path)

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._touch()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)


_writer: HeartbeatWriter | None = None


def start_heartbeat(interval: float = 1.0):
    """Start this process's heartbeat if the launcher asked for one
    (PADDLE_ELASTIC_HEARTBEAT_DIR set). Idempotent; called by training
    entry points (TrainEpochRange does it automatically)."""
    global _writer
    dir_ = os.environ.get("PADDLE_ELASTIC_HEARTBEAT_DIR")
    if not dir_ or _writer is not None:
        return _writer
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    _writer = HeartbeatWriter(dir_, rank, interval).start()
    return _writer


def stale_ranks(dir_: str, timeout: float, expected: int,
                grace: float = 0.0) -> list[int]:
    """Ranks whose heartbeat file is missing-after-grace or older than
    `timeout` seconds. Ranks that never wrote a file are only reported
    once SOME rank has (otherwise scripts that don't opt in would always
    look hung), and — when `grace` > 0 — only once the job has been
    beating for at least `grace` seconds (slow ranks legitimately write
    their first heartbeat later than fast ones; the launcher passes its
    heartbeat timeout here)."""
    now = time.time()
    seen_any = False
    stale = []
    ages = {}
    job_age = None
    for r in range(expected):
        p = _hb_path(dir_, r)
        try:
            mtime = os.path.getmtime(p)
            ages[r] = now - mtime
            seen_any = True
        except OSError:
            ages[r] = None
            continue
        # job age from the writer's recorded "start now" stamp pair —
        # only read when a grace window is in play. Only genuine
        # two-token stamps count: pre-upgrade writers wrote a single
        # PER-BEAT timestamp, and reading that (or the fresh file
        # mtime) as a start stamp would pin job_age near zero for as
        # long as the rank keeps beating — grace would never expire
        # and never-written ranks would never be reported
        if grace <= 0:
            continue
        try:
            with open(p) as f:
                tokens = f.read().split()
            if len(tokens) >= 2:
                age0 = now - float(tokens[0])
                job_age = age0 if job_age is None \
                    else max(job_age, age0)
        except (OSError, ValueError):
            pass
    if not seen_any:
        return []
    # no start stamps at all (all-legacy writers): grace disabled,
    # legacy missing-rank reporting applies
    in_grace = grace > 0 and job_age is not None and job_age < grace
    for r, age in ages.items():
        if age is None:
            if not in_grace:
                stale.append(r)
        elif age > timeout:
            stale.append(r)
    return stale


class ElasticManager:
    """API-parity facade (reference fleet/elastic/manager.py): wraps the
    watchdog decision — should the job restart, and how many lives are
    left. PS mode additionally tracks SINGLE-SERVER restarts: a dead PS
    shard whose state lives in snapshots is respawned in place (workers'
    transport retry loops reconnect and resume) without burning a
    whole-job restart."""

    def __init__(self, max_restarts: int = 0, heartbeat_timeout: float = 30.0,
                 heartbeat_dir: str | None = None, world_size: int = 1,
                 max_server_restarts: int | None = None,
                 startup_grace: float | None = None):
        self.max_restarts = max_restarts
        self.heartbeat_timeout = heartbeat_timeout
        self.heartbeat_dir = heartbeat_dir
        self.world_size = world_size
        self.restart_count = 0
        self.max_server_restarts = max_restarts \
            if max_server_restarts is None else max_server_restarts
        self.server_restart_count = 0
        self.startup_grace = heartbeat_timeout \
            if startup_grace is None else startup_grace

    def should_restart(self) -> bool:
        return self.restart_count < self.max_restarts

    def record_restart(self):
        self.restart_count += 1

    def should_restart_server(self) -> bool:
        return self.server_restart_count < self.max_server_restarts

    def record_server_restart(self):
        self.server_restart_count += 1

    def hung_ranks(self) -> list[int]:
        if not self.heartbeat_dir:
            return []
        return stale_ranks(self.heartbeat_dir, self.heartbeat_timeout,
                           self.world_size, grace=self.startup_grace)
