"""Elastic training: heartbeats, hang detection, job-level restart.

Reference: python/paddle/distributed/fleet/elastic/* (ElasticManager
watching etcd heartbeats, restarting the pod on scale events or dead
nodes). TPU build: no etcd — heartbeats are files in a shared
directory (PADDLE_ELASTIC_HEARTBEAT_DIR), the launcher's watchdog
(distributed/launch.py --max_restarts) is the manager: a crashed or
hung rank tears the whole job down and respawns it; training scripts
resume from their latest coordinated checkpoint
(distributed/cluster_ckpt.py, or incubate/checkpoint.py
TrainEpochRange), which is exactly the reference's pod-restart
recovery contract — XLA collectives cannot re-admit a single lost
rank mid-step any more than NCCL could.

Heartbeat content is ``"start_ts beat_ts step"`` — three
space-separated tokens. Staleness is decided on the CONTENT, not the
file mtime: the watcher (ElasticManager) tracks when each rank's
content last CHANGED on its own monotonic clock, so NFS mtime
granularity or cross-host clock skew cannot kill a healthy rank. The
step token splits "hung" (step frozen past ``step_deadline`` →
restart) from "merely slow" (step-lag straggler → flagged via
``paddle_tpu_elastic_*`` metrics and a flight event, never killed).
"""
from __future__ import annotations

import os
import threading
import time

from ..observability import flight as _flight, registry as _obs

__all__ = ["HeartbeatWriter", "start_heartbeat", "note_step",
           "read_heartbeats", "stale_ranks", "ElasticManager"]

_HB_SUFFIX = ".hb"

_HEARTBEATS = _obs.counter(
    "paddle_tpu_elastic_heartbeats_total",
    "heartbeat file writes by this process")
_STALE_RANKS = _obs.gauge(
    "paddle_tpu_elastic_stale_ranks",
    "ranks currently considered hung (stale heartbeat content or "
    "step frozen past deadline)")
_STRAGGLER_RANKS = _obs.gauge(
    "paddle_tpu_elastic_straggler_ranks",
    "ranks flagged slow-but-progressing (step lag over threshold; "
    "never killed)")
_STEP_LAG = _obs.gauge(
    "paddle_tpu_elastic_step_lag",
    "largest step lag behind the fastest rank at the last poll")
_RESTARTS = _obs.counter(
    "paddle_tpu_elastic_restarts_total",
    "whole-job elastic restarts, by trigger", ["reason"])
_GIVEUPS = _obs.counter(
    "paddle_tpu_elastic_crash_loop_giveups_total",
    "jobs abandoned by crash-loop detection (K failures in a window)")


def _hb_path(dir_, rank):
    return os.path.join(dir_, f"rank{rank}{_HB_SUFFIX}")


class HeartbeatWriter:
    """Writes this rank's heartbeat file every `interval` seconds from
    a daemon thread. Content is ``"start_ts beat_ts step"``; training
    loops feed the step via ``set_step`` (``note_step`` does it) so
    the launcher can tell a hung rank from a slow one."""

    def __init__(self, dir_: str, rank: int, interval: float = 1.0):
        self.path = _hb_path(dir_, rank)
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None
        self._start_ts = None
        self._step = -1          # -1 = no step reported yet

    def start(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._start_ts = time.time()
        self._touch()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def set_step(self, step: int):
        """Record training progress; the next beat carries it. Cheap
        enough for every step (an int store — no IO on the step path).
        """
        self._step = int(step)

    def _touch(self):
        # "start beat step" content lets stale_ranks compute the job's
        # age (the startup grace window for ranks that haven't opted
        # in yet) and the watcher read progress. Write-then-rename: a
        # truncate-in-place write could be torn by a concurrent
        # stale_ranks read into a garbage start_ts that ends the grace
        # window early
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{self._start_ts} {time.time()} {self._step}")
        os.replace(tmp, self.path)
        _HEARTBEATS.inc()

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._touch()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)


_writer: HeartbeatWriter | None = None


def start_heartbeat(interval: float = 1.0):
    """Start this process's heartbeat if the launcher asked for one
    (PADDLE_ELASTIC_HEARTBEAT_DIR set). Idempotent; called by training
    entry points (hapi fit / TrainEpochRange do it automatically)."""
    global _writer
    dir_ = os.environ.get("PADDLE_ELASTIC_HEARTBEAT_DIR")
    if not dir_ or _writer is not None:
        return _writer
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    _writer = HeartbeatWriter(dir_, rank, interval).start()
    return _writer


def note_step(step: int):
    """Training loops call this once per step: publishes progress to
    the heartbeat (hang-vs-slow discrimination) and gives the fault
    injector its deterministic trainer-side hook
    (PADDLE_PS_FAULT_KILL_AT_STEP / STALL_POINT=trainer_step)."""
    w = _writer
    if w is not None:
        w.set_step(step)
    try:  # lazy: fleet package is heavier than this module
        from .fleet.runtime.fault_injection import injector
    except ImportError:  # pragma: no cover - fleet always ships
        return
    inj = injector()
    inj.maybe_kill_at_step(step)
    inj.maybe_stall("trainer_step")


def read_heartbeats(dir_: str, expected: int) -> dict:
    """Parse every expected rank's heartbeat file. Returns rank →
    ``{"start", "beat", "step", "mtime", "raw"}`` (fields None when
    unparseable / pre-upgrade formats) or None for a missing file.
    Legacy formats: one token = per-beat timestamp (no start, no
    step); two tokens = "start beat" (no step)."""
    out = {}
    for r in range(expected):
        p = _hb_path(dir_, r)
        try:
            mtime = os.path.getmtime(p)
            with open(p) as f:
                raw = f.read()
        except OSError:
            out[r] = None
            continue
        info = {"start": None, "beat": None, "step": None,
                "mtime": mtime, "raw": raw}
        tokens = raw.split()
        try:
            if len(tokens) == 1:
                info["beat"] = float(tokens[0])
            elif len(tokens) >= 2:
                info["start"] = float(tokens[0])
                info["beat"] = float(tokens[1])
                if len(tokens) >= 3:
                    step = int(tokens[2])
                    info["step"] = step if step >= 0 else None
        except ValueError:
            pass
        out[r] = info
    return out


def stale_ranks(dir_: str, timeout: float, expected: int,
                grace: float = 0.0, tracker: dict | None = None) \
        -> list[int]:
    """Ranks whose heartbeat is missing-after-grace or stale past
    `timeout` seconds. Staleness comes from heartbeat CONTENT, never
    the file mtime (NFS mtime granularity / clock skew must not kill
    a healthy rank):

    - with ``tracker`` (a dict the caller keeps across polls — the
      ElasticManager path): age since the content last CHANGED,
      measured on THIS process's monotonic clock. Fully skew-proof.
    - stateless calls: age of the beat timestamp written in the file
      (same clock as the writer's start stamp). mtime is only the
      last resort for unparseable content.

    Ranks that never wrote a file are only reported once SOME rank
    has (otherwise scripts that don't opt in would always look hung),
    and — when `grace` > 0 — only once the job has been beating for
    at least `grace` seconds (slow ranks legitimately write their
    first heartbeat later than fast ones; the launcher passes its
    heartbeat timeout here)."""
    now = time.time()
    mono = time.monotonic()
    hbs = read_heartbeats(dir_, expected)
    if not any(h is not None for h in hbs.values()):
        return []
    # job age from genuine start stamps only: a single-token legacy
    # PER-BEAT timestamp (or the fresh mtime) read as a start stamp
    # would pin job_age near zero for as long as the rank keeps
    # beating — grace would never expire and never-written ranks
    # would never be reported
    job_age = None
    if grace > 0:
        for h in hbs.values():
            if h is not None and h["start"] is not None:
                age0 = now - h["start"]
                job_age = age0 if job_age is None \
                    else max(job_age, age0)
    in_grace = grace > 0 and job_age is not None and job_age < grace
    stale = []
    for r, h in hbs.items():
        if h is None:
            if not in_grace:
                stale.append(r)
            continue
        if tracker is not None:
            prev = tracker.get(r)
            if prev is None or prev[0] != h["raw"]:
                tracker[r] = (h["raw"], mono)
                age = 0.0
            else:
                age = mono - prev[1]
        elif h["beat"] is not None:
            age = now - h["beat"]
        else:
            age = now - h["mtime"]
        if age > timeout:
            stale.append(r)
    return stale


class ElasticManager:
    """API-parity facade (reference fleet/elastic/manager.py): wraps
    the watchdog decision — should the job restart, and how many lives
    are left. PS mode additionally tracks SINGLE-SERVER restarts: a
    dead PS shard whose state lives in snapshots is respawned in place
    (workers' transport retry loops reconnect and resume) without
    burning a whole-job restart.

    Progress awareness: ``hung_ranks()`` reads heartbeat content once
    per poll and splits ranks three ways — hung (stale content, or
    step frozen past ``step_deadline`` while some other rank still
    advances), straggler (``straggler_lag``+ steps behind the fastest
    rank — flagged via metrics + flight event, never killed), and
    healthy. A rank frozen AT the max step is excused while any rank
    advances: it is blocked on the straggler at a collective, not
    hung itself. When every rank is frozen past the deadline the whole
    gang is hung (deadlocked collective) and all are reported."""

    def __init__(self, max_restarts: int = 0,
                 heartbeat_timeout: float = 30.0,
                 heartbeat_dir: str | None = None, world_size: int = 1,
                 max_server_restarts: int | None = None,
                 startup_grace: float | None = None,
                 step_deadline: float = 0.0,
                 straggler_lag: int = 10):
        self.max_restarts = max_restarts
        self.heartbeat_timeout = heartbeat_timeout
        self.heartbeat_dir = heartbeat_dir
        self.world_size = world_size
        self.restart_count = 0
        self.max_server_restarts = max_restarts \
            if max_server_restarts is None else max_server_restarts
        self.server_restart_count = 0
        self.startup_grace = heartbeat_timeout \
            if startup_grace is None else startup_grace
        self.step_deadline = float(step_deadline)
        self.straggler_lag = int(straggler_lag)
        self._tracker: dict = {}    # rank -> (raw content, mono ts)
        self._steps: dict = {}      # rank -> (step, mono ts advanced)
        self._flagged: set = set()  # stragglers already flight-logged
        self._stragglers: list = []

    def should_restart(self) -> bool:
        return self.restart_count < self.max_restarts

    def record_restart(self, reason: str = "crash"):
        self.restart_count += 1
        _RESTARTS.labels(reason=reason).inc()
        _flight.record("elastic", "job_restart", reason=reason,
                       attempt=self.restart_count,
                       budget=self.max_restarts)

    def record_giveup(self, reason: str, offender=None):
        _GIVEUPS.inc()
        _flight.record("elastic", "give_up", reason=reason,
                       offender=offender,
                       restarts=self.restart_count)

    def should_restart_server(self) -> bool:
        return self.server_restart_count < self.max_server_restarts

    def record_server_restart(self):
        self.server_restart_count += 1

    def reset_epoch(self):
        """Forget per-life observation state (call after every respawn
        — and after an exclusion resize, where ranks renumber)."""
        self._tracker.clear()
        self._steps.clear()
        self._flagged.clear()
        self._stragglers = []

    def hung_ranks(self) -> list[int]:
        """One watchdog poll: hung ranks to act on. Also refreshes
        ``stragglers()`` and the ``paddle_tpu_elastic_*`` gauges."""
        if not self.heartbeat_dir:
            return []
        stale = stale_ranks(self.heartbeat_dir,
                            self.heartbeat_timeout, self.world_size,
                            grace=self.startup_grace,
                            tracker=self._tracker)
        hbs = read_heartbeats(self.heartbeat_dir, self.world_size)
        now = time.monotonic()
        steps = {r: h["step"] for r, h in hbs.items()
                 if h is not None and h["step"] is not None}
        frozen = []
        for r, s in steps.items():
            prev = self._steps.get(r)
            if prev is None or s > prev[0]:
                self._steps[r] = (s, now)
            elif self.step_deadline > 0 \
                    and now - prev[1] > self.step_deadline:
                frozen.append(r)
        if frozen and steps:
            max_step = max(steps.values())
            if len(frozen) < len(steps):
                # somebody still advances: a frozen rank AT the front
                # is merely blocked on the laggards at a collective
                frozen = [r for r in frozen if steps[r] < max_step]
        hung = sorted(set(stale) | set(frozen))
        # stragglers: behind the front but still moving — flag, never
        # kill
        stragglers = []
        max_lag = 0
        if steps:
            max_step = max(steps.values())
            for r, s in steps.items():
                lag = max_step - s
                max_lag = max(max_lag, lag)
                if r not in hung and lag > self.straggler_lag:
                    stragglers.append(r)
                    if r not in self._flagged:
                        self._flagged.add(r)
                        _flight.record("elastic", "straggler",
                                       rank=r, step=s, lag=lag,
                                       threshold=self.straggler_lag)
        self._stragglers = sorted(stragglers)
        _STALE_RANKS.set(len(hung))
        _STRAGGLER_RANKS.set(len(self._stragglers))
        _STEP_LAG.set(max_lag)
        return hung

    def stragglers(self) -> list[int]:
        """Slow-but-progressing ranks from the LAST ``hung_ranks()``
        poll."""
        return list(self._stragglers)
