"""Filesystem abstraction: LocalFS + HDFSClient (reference
python/paddle/distributed/fleet/utils/fs.py — itself the checkpoint
tier's storage backend, incubate/checkpoint auto_checkpoint fs arg).

LocalFS is fully functional; HDFSClient shells out to `hadoop fs` when a
hadoop binary is configured and raises a clear error otherwise (hermetic
environments have no HDFS — the API surface still lets checkpoint code
take an `fs` parameter portably).
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def touch(self, path, exist_ok=True):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem backend (reference fs.py LocalFS)."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, e))
             else files).append(e)
        return dirs, files

    def is_file(self, path):
        return os.path.isfile(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)

    def mv(self, src, dst, overwrite=False):
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FSFileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path) and not exist_ok:
            raise FSFileExistsError(path)
        open(path, "a").close()

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)


class HDFSClient(FS):
    """`hadoop fs` subprocess wrapper (reference fs.py HDFSClient).
    Needs a hadoop binary: pass hadoop_home or have `hadoop` on PATH."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]
        self._timeout = time_out

    def _run(self, *args):
        if self._hadoop is None:
            raise RuntimeError(
                "HDFSClient needs a hadoop binary (hadoop_home= or "
                "`hadoop` on PATH); this environment has none — use "
                "LocalFS")
        res = subprocess.run(
            [self._hadoop, "fs"] + self._cfg + list(args),
            capture_output=True, text=True, timeout=self._timeout)
        return res.returncode, res.stdout

    def ls_dir(self, path):
        rc, out = self._run("-ls", path)
        if rc != 0:
            return [], []
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return self._run("-test", "-e", path)[0] == 0

    def is_file(self, path):
        return self._run("-test", "-f", path)[0] == 0

    def is_dir(self, path):
        return self._run("-test", "-d", path)[0] == 0

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, path, exist_ok=True):
        if not exist_ok and self.is_exist(path):
            raise FSFileExistsError(path)
        self._run("-touchz", path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)
