"""Coordinated cluster checkpoints + world-resize resume.

The elastic-training substrate: every rank of a collective job
snapshots its share of (params, optimizer slots, RNG streams, data
cursor) through the content-addressed store's multi-host part API,
tagged with the SAME step id, and rank 0 merge-commits — one atomic
manifest per cluster version. A kill anywhere before the merge rename
leaves the previous version restorable bit-for-bit.

Layout kinds (recorded in the manifest meta, drive the resume path):

- ``replicated`` — identical on every rank (dp params, scalar step
  counters). Saved once by rank 0 under its plain name; restore
  broadcasts the full array to every new rank.
- ``sharded`` — axis-0 partitioned across ranks (np.array_split
  convention). Each rank publishes its piece as ``name@shardNNNN``;
  restore to ANY world size stitches the pieces and re-cuts them on
  the new partition, reading only the overlapping chunks.
- ``per_rank`` — private, world-shaped state (RNG counters). Saved as
  ``name@rankNNNN``; restored exactly only at the SAME world size,
  otherwise ``None`` — callers re-derive it counter-style from
  (seed, step), which is why ``SampleSchedule`` below exists.

Cadence: ``maybe_save(step, ...)`` fires on a step modulus
(``every_steps``, the coordinated default — all ranks agree with no
traffic) and/or a seconds budget: rank 0 publishes an *intent file*
one step ahead, every rank polls it at the next ``maybe_save`` and
joins the save at that agreed step. Async saves ride the store's one
persistent writer thread (host copies now, IO off the step path).

Resume ordering across a resize (``SampleSchedule``): the sample
permutation is counter-based Philox keyed by (seed, epoch) — any
(rank, world) can regenerate it without state, so after a W→W'
restart the REMAINING samples repartition deterministically and the
global batch composition per step is world-invariant. That is what
makes the resumed loss curve continue the fault-free run's.
"""
from __future__ import annotations

import os
import time

import numpy as np

from ..observability import flight as _flight, registry as _obs
from ..checkpoint import CheckpointStore
from ..checkpoint import manifest as _manifest

__all__ = ["ClusterCheckpoint", "SampleSchedule",
           "ClusterCheckpointError"]

_RESUME_SECONDS = _obs.histogram(
    "paddle_tpu_elastic_resume_seconds",
    "wall time of one cluster-checkpoint restore (resharding incl.)")

# domain-separation constant for the sample-order Philox key ("elas")
_SCHEDULE_TAG = 0x656C6173


class ClusterCheckpointError(RuntimeError):
    pass


def _env_opt_int(name: str) -> int | None:
    v = os.environ.get(name, "")
    return int(v) if v else None


def _env_opt_float(name: str) -> float | None:
    v = os.environ.get(name, "")
    return float(v) if v else None


class SampleSchedule:
    """Counter-based sample-order schedule keyed by (seed, epoch).

    The epoch permutation comes from a Philox generator whose key is
    (seed, epoch, tag) — no mutable RNG state survives a restart, so
    every rank of every world size regenerates the identical order.
    ``global_indices(step)`` is world-invariant; ``rank_indices``
    slices each rank's even share of the SAME global batch, so a
    resumed run at a different world consumes the remaining samples
    in the same global order with the same batch composition.
    """

    def __init__(self, seed: int, epoch: int, num_samples: int,
                 global_batch: int):
        if num_samples <= 0 or global_batch <= 0:
            raise ValueError("num_samples and global_batch must be "
                             "positive")
        if global_batch > num_samples:
            raise ValueError("global_batch larger than the epoch")
        self.seed, self.epoch = int(seed), int(epoch)
        self.num_samples = int(num_samples)
        self.global_batch = int(global_batch)
        self.steps_per_epoch = self.num_samples // self.global_batch
        mask = (1 << 64) - 1
        # 128-bit Philox key: seed word + (epoch, domain-tag) word
        key = np.array([self.seed & mask,
                        ((self.epoch & 0xFFFFFFFF) << 32)
                        | _SCHEDULE_TAG & mask], dtype=np.uint64)
        rng = np.random.Generator(np.random.Philox(key=key))
        self.perm = rng.permutation(self.num_samples)

    def global_indices(self, step: int) -> np.ndarray:
        """Sample ids of this epoch's batch at ``step`` (epoch-local:
        steps fold onto ``steps_per_epoch``; advance ``epoch`` in the
        key for the next pass)."""
        s = int(step) % self.steps_per_epoch
        lo = s * self.global_batch
        return self.perm[lo:lo + self.global_batch]

    def rank_indices(self, step: int, rank: int, world: int) \
            -> np.ndarray:
        """Rank ``rank``'s slice of the step's global batch. The
        global batch must divide evenly — the resize rule documented
        in docs/ELASTIC.md (keep ``global_batch`` a multiple of every
        world size you may shrink to)."""
        if world <= 0 or not 0 <= rank < world:
            raise ValueError(f"rank {rank} outside [0, {world})")
        if self.global_batch % world:
            raise ValueError(
                f"global_batch {self.global_batch} not divisible by "
                f"world {world} — pick a resize-compatible batch")
        g = self.global_indices(step)
        per = self.global_batch // world
        return g[rank * per:(rank + 1) * per]

    def remaining(self, next_step: int) -> np.ndarray:
        """Sample ids this epoch not yet consumed when the next step
        to run is ``next_step`` — the set a resumed world repartitions."""
        s = int(next_step) % self.steps_per_epoch
        return self.perm[s * self.global_batch:
                         self.steps_per_epoch * self.global_batch]


def _decor(name: str, kind: str, rank: int) -> str:
    if kind == "sharded":
        return f"{name}@shard{rank:04d}"
    if kind == "per_rank":
        return f"{name}@rank{rank:04d}"
    return name


class ClusterCheckpoint:
    """One rank's handle on the coordinated checkpoint of a collective
    job. All ranks construct it over the same ``root`` (shared fs)
    and call ``maybe_save(step, ...)`` every step with their share of
    the state; restore reshards to whatever (rank, world) is asking.
    """

    def __init__(self, root: str, rank: int | None = None,
                 world: int | None = None,
                 every_steps: int | None = None,
                 every_seconds: float | None = None,
                 async_save: bool | None = None,
                 merge_timeout: float = 60.0,
                 store: CheckpointStore | None = None):
        env = os.environ.get
        self.root = root
        self.rank = int(rank if rank is not None
                        else env("PADDLE_TRAINER_ID", "0"))
        self.world = int(world if world is not None
                         else env("PADDLE_TRAINERS_NUM", "1"))
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"rank {self.rank} outside world {self.world}")
        self.every_steps = every_steps if every_steps is not None \
            else _env_opt_int("PADDLE_TPU_CKPT_EVERY_STEPS")
        self.every_seconds = every_seconds if every_seconds is not None \
            else _env_opt_float("PADDLE_TPU_CKPT_EVERY_SECONDS")
        if async_save is None:
            async_save = env("PADDLE_TPU_CKPT_ASYNC", "1") \
                not in ("", "0", "false")
        self.async_save = bool(async_save)
        self.merge_timeout = float(merge_timeout)
        self.store = store or CheckpointStore(root)
        self._last_save_t = time.monotonic()

    # -- cadence --------------------------------------------------------
    def _intent_path(self, step: int) -> str:
        return os.path.join(self.root, f"intent-{step:010d}.json")

    def _write_intent(self, step: int):
        import json
        os.makedirs(self.root, exist_ok=True)
        tmp = self._intent_path(step) + f".tmp{self.rank}"
        with open(tmp, "w") as f:
            json.dump({"step": int(step)}, f)
        os.replace(tmp, self._intent_path(step))

    def _intent_pending(self, step: int) -> bool:
        return os.path.exists(self._intent_path(step))

    def due(self, step: int) -> bool:
        """Is a coordinated save agreed for ``step``? Pure function of
        (step modulus, intent files) so every rank answers alike."""
        if self.every_steps and step > 0 \
                and step % self.every_steps == 0:
            return True
        return self._intent_pending(step)

    def maybe_save(self, step: int, replicated=None, sharded=None,
                   per_rank=None, extra_meta=None) -> int | None:
        """Save iff ``step`` is a coordinated save point; returns the
        step saved or None. Rank 0 additionally arms the seconds
        cadence by publishing an intent for ``step + 1`` — one step of
        lead so every rank sees it in time. A rank that diverges past
        an intent simply skips that version (the merge times out and
        the previous manifest stays current — degraded, never torn).
        """
        fire = self.due(step)
        if self.rank == 0 and self.every_seconds and not fire \
                and not self._intent_pending(step + 1) \
                and time.monotonic() - self._last_save_t \
                >= self.every_seconds:
            self._write_intent(step + 1)
        if not fire:
            return None
        return self.save(step, replicated=replicated, sharded=sharded,
                         per_rank=per_rank, extra_meta=extra_meta)

    # -- save -----------------------------------------------------------
    def _build_part(self, replicated, sharded, per_rank):
        replicated = dict(replicated or {})
        sharded = dict(sharded or {})
        per_rank = dict(per_rank or {})
        layout = {}
        for d, kind in ((replicated, "replicated"),
                        (sharded, "sharded"), (per_rank, "per_rank")):
            for name in d:
                if name in layout:
                    raise ValueError(
                        f"{name}: appears under two layout kinds")
                if "@" in name:
                    raise ValueError(
                        f"{name}: '@' is reserved for shard/rank "
                        "decoration")
                layout[name] = kind
        for name, val in sharded.items():
            if np.asarray(val).ndim == 0:
                raise ValueError(
                    f"{name}: scalars cannot be sharded — declare it "
                    "replicated")
        part = {}
        if self.rank == 0:
            part.update(replicated)
        for name, val in sharded.items():
            part[_decor(name, "sharded", self.rank)] = val
        for name, val in per_rank.items():
            part[_decor(name, "per_rank", self.rank)] = val
        return part, layout

    def save(self, step: int, replicated=None, sharded=None,
             per_rank=None, extra_meta=None) -> int:
        """Commit this rank's part of cluster version ``step`` (and,
        on rank 0, the merge). With ``async_save`` both ride the
        store's writer thread and the step returns immediately."""
        step = int(step)
        part, layout = self._build_part(replicated, sharded, per_rank)
        meta = {"cluster": {"world": self.world, "layout": layout,
                            "extra": extra_meta}}
        _flight.record("elastic", "cluster_save", step=step,
                       rank=self.rank, world=self.world,
                       mode="async" if self.async_save else "sync")
        if self.async_save:
            self.store.save_part_async(part, step, self.rank,
                                       self.world)
            if self.rank == 0:
                self.store.merge_parts_async(
                    step, self.world, meta=meta,
                    timeout=self.merge_timeout)
        else:
            self.store.save_part(part, step, self.rank, self.world)
            if self.rank == 0:
                deadline = time.monotonic() + self.merge_timeout
                while len(_manifest.list_parts(self.root, step)) \
                        < self.world:
                    if time.monotonic() >= deadline:
                        raise ClusterCheckpointError(
                            f"step {step}: missing parts after "
                            f"{self.merge_timeout}s")
                    time.sleep(0.02)
                self.store.merge_parts(step, self.world, meta=meta)
        self._last_save_t = time.monotonic()
        if self.rank == 0:
            self._gc_intents(step)
        return step

    def _gc_intents(self, upto: int):
        """Drop consumed intent files (best-effort; they are tiny)."""
        import glob
        for p in glob.glob(os.path.join(self.root, "intent-*.json")):
            try:
                if int(os.path.basename(p)[7:-5]) <= upto:
                    os.unlink(p)
            except (ValueError, OSError):
                pass

    def wait(self):
        """Drain this rank's pending async writes (surfacing errors).
        A merge timeout surfaces here as ManifestError — the job keeps
        the previous restorable version."""
        self.store.wait()

    # -- restore --------------------------------------------------------
    @staticmethod
    def exists(root: str) -> bool:
        return CheckpointStore.exists(root)

    def restore(self, rank: int | None = None,
                world: int | None = None,
                step: int | None = None) -> tuple[dict, dict]:
        """(state, info) of the newest committed cluster version,
        resharded for (rank, world) — defaults to this handle's.
        ``state`` maps the ORIGINAL names: replicated arrays in full,
        sharded arrays cut on the new world's np.array_split
        partition, per_rank arrays exactly at the same world else
        ``None``. ``info`` carries step / saved_world / extra."""
        t0 = time.perf_counter()
        rank = self.rank if rank is None else int(rank)
        world = self.world if world is None else int(world)
        payload = self.store.latest_manifest(step)
        meta = payload.get("meta") or {}
        cluster = meta.get("cluster")
        if cluster is None:
            raise ClusterCheckpointError(
                f"{self.root}: manifest at step {payload['step']} has "
                "no cluster layout — not a coordinated checkpoint")
        saved_world = int(cluster["world"])
        layout = cluster["layout"]
        arrays = payload["arrays"]
        state: dict = {}
        for name, kind in layout.items():
            if kind == "replicated":
                state[name] = self.store.materialize(arrays[name])
            elif kind == "sharded":
                state[name] = self._restore_resharded(
                    arrays, name, saved_world, rank, world)
            else:  # per_rank
                key = _decor(name, "per_rank", rank)
                state[name] = self.store.materialize(arrays[key]) \
                    if world == saved_world and key in arrays else None
        info = {"step": int(payload["step"]),
                "saved_world": saved_world,
                "extra": cluster.get("extra")}
        if rank == 0:
            # leftovers of the torn save the crash interrupted: purge
            # uncommitted parts/intents past the committed step so a
            # resumed (possibly resized) gang can never merge a stale
            # piece into a fresh version (merge_parts also rejects
            # wrong-world parts — this keeps the dir clean)
            self._purge_stale(int(payload["step"]))
        dt = time.perf_counter() - t0
        _RESUME_SECONDS.observe(dt)
        _flight.record("elastic", "cluster_restore",
                       step=info["step"], rank=rank, world=world,
                       saved_world=saved_world, seconds=round(dt, 6))
        return state, info

    def _purge_stale(self, committed_step: int):
        import glob
        for pat, off in (("part-*.json", 5), ("intent-*.json", 7)):
            for p in glob.glob(os.path.join(self.root, pat)):
                base = os.path.basename(p)
                try:
                    if int(base[off:off + 10]) > committed_step:
                        os.unlink(p)
                except (ValueError, OSError):
                    pass

    def _restore_resharded(self, arrays: dict, name: str,
                           saved_world: int, rank: int,
                           world: int) -> np.ndarray:
        """Stitch the saved per-rank pieces of ``name`` and cut this
        rank's np.array_split share of the new world, reading only the
        byte ranges that overlap (piece chunks are never fully read
        unless owned)."""
        pieces, row0 = [], 0
        for r in range(saved_world):
            ent = arrays.get(_decor(name, "sharded", r))
            if ent is None:
                raise ClusterCheckpointError(
                    f"{name}: missing shard piece for saved rank {r}")
            shape = tuple(ent["shape"])
            if not shape:
                raise ClusterCheckpointError(
                    f"{name}: scalar shard piece cannot be resharded")
            pieces.append((row0, shape[0], ent))
            row0 += shape[0]
        total = row0
        base, rem = divmod(total, world)
        lo = rank * base + min(rank, rem)
        hi = lo + base + (1 if rank < rem else 0)
        first = pieces[0][2]
        trailing = tuple(first["shape"][1:])
        dtype = np.dtype(first["dtype"])
        if lo == hi:
            return np.empty((0,) + trailing, dtype=dtype)
        out = []
        for r0, rows, ent in pieces:
            a, b = max(lo, r0), min(hi, r0 + rows)
            if a >= b:
                continue
            out.append(self.store.read_rows(ent, a - r0, b - r0))
        return out[0] if len(out) == 1 else np.concatenate(out, axis=0)

    def latest_step(self) -> int | None:
        steps = self.store.steps()
        return steps[-1] if steps else None
