"""Global device-mesh management.

The mesh replaces the reference's ring_id/comm registry
(platform/collective_helper.h:62): collectives name mesh AXES instead of
rings; XLA routes them over ICI (intra-slice) / DCN (inter-slice).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["default_mesh", "get_mesh", "set_mesh", "make_mesh"]

_mesh: Mesh | None = None


def make_mesh(axes: dict[str, int] | None = None) -> Mesh:
    """Build a Mesh from {axis_name: size}; sizes must multiply to the device
    count (-1 allowed once as wildcard)."""
    devs = np.array(jax.devices())
    if not axes:
        return Mesh(devs.reshape(-1), ("dp",))
    names = tuple(axes)
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    return Mesh(devs.reshape(sizes), names)


def default_mesh() -> Mesh:
    global _mesh
    if _mesh is None:
        _mesh = make_mesh(None)
    return _mesh


def get_mesh() -> Mesh | None:
    return _mesh


def set_mesh(mesh: Mesh | dict | None):
    global _mesh
    _mesh = make_mesh(mesh) if isinstance(mesh, dict) or mesh is None \
        else mesh
    return _mesh
