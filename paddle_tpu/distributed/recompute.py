"""Activation recompute (gradient checkpointing) for the eager/functional
path.

Reference: paddle.distributed.fleet.utils.recompute and RecomputeOptimizer
(/root/reference/python/paddle/fluid/optimizer.py:4518). TPU-native
mechanism: `jax.checkpoint` (remat) over the layer's traced computation —
inside a functional trace (TrainStep / to_static) XLA drops the wrapped
segment's activations after forward and re-derives them during backward,
trading ~1/3 more FLOPs for O(sqrt) activation memory.

In pure eager mode (tape autograd, no surrounding jax trace) the wrapper is
a transparent pass-through: the tape already holds inputs, and remat buys
nothing without a compiled backward. The memory win applies under
make_train_step/to_static, which is where long-sequence training runs.
"""
from __future__ import annotations

from typing import Any, Callable

__all__ = ["recompute", "wrap_layer_recompute"]


def _flatten_tensors(args: tuple, kwargs: dict):
    """Split (args, kwargs) into traced tensor leaves + a rebuild fn."""
    from ..fluid.dygraph.varbase import Tensor
    leaves = []
    spec = []

    def scan(x):
        if isinstance(x, Tensor):
            spec.append(("t", x.stop_gradient))
            leaves.append(x._value)
        else:
            spec.append(("s", x))

    for a in args:
        scan(a)
    keys = sorted(kwargs)
    for k in keys:
        scan(kwargs[k])

    def rebuild(vals):
        from ..fluid.dygraph.varbase import Tensor
        it = iter(vals)
        out = []
        for kind, payload in spec:
            if kind == "t":
                t = Tensor(next(it), stop_gradient=payload)
                out.append(t)
            else:
                out.append(payload)
        na = out[: len(args)]
        nk = dict(zip(keys, out[len(args):]))
        return na, nk

    return leaves, rebuild


def recompute(function: Callable, *args, preserve_rng_state: bool = True,
              **kwargs) -> Any:
    """Run `function(*args, **kwargs)` under jax.checkpoint so its internal
    activations are rematerialised in the backward pass.

    Tensor arguments are differentiated through; non-tensor arguments are
    closed over statically. Returns Tensor / tuple-of-Tensor like the
    wrapped function."""
    import jax
    from ..fluid.dygraph.varbase import Tensor

    leaves, rebuild = _flatten_tensors(args, kwargs)
    in_trace = any(isinstance(v, jax.core.Tracer) for v in leaves) or \
        _params_traced(function)
    if not in_trace:
        # pure eager (tape) mode: remat buys nothing without a compiled
        # backward, and routing the tape through rebuilt tensors would
        # detach gradients — transparent pass-through
        return function(*args, **kwargs)

    def pure(*vals):
        na, nk = rebuild(vals)
        res = function(*na, **nk)
        if isinstance(res, (list, tuple)):
            return tuple(r._value if isinstance(r, Tensor) else r
                         for r in res)
        return res._value if isinstance(res, Tensor) else res

    out_vals = jax.checkpoint(pure)(*leaves)
    if isinstance(out_vals, tuple):
        return tuple(Tensor(v) if v is not None else None for v in out_vals)
    return Tensor(out_vals)


def _params_traced(function) -> bool:
    """Whether the function's bound layer (if any) holds traced params —
    the TrainStep trace binds tracer values into eager params, so the args
    alone don't reveal the trace."""
    import jax
    layer = getattr(function, "__self__", None)
    if layer is None:
        return False
    try:
        for p in layer.parameters():
            return isinstance(p._value, jax.core.Tracer)
    except Exception:  # pragma: no cover
        return False
    return False


def _remat_unit_types():
    from .. import nn
    return (nn.TransformerEncoderLayer, nn.TransformerDecoderLayer)


def wrap_layer_recompute(model) -> int:
    """Wrap every transformer-layer sublayer of `model` so its forward runs
    under `recompute`. Returns the number of layers wrapped. Idempotent."""
    units = _remat_unit_types()
    n = 0
    for sub in model.sublayers(include_self=True):
        if isinstance(sub, units) and not getattr(sub, "_remat_wrapped",
                                                  False):
            orig = sub.forward

            def wrapped(*a, _orig=orig, **kw):
                return recompute(_orig, *a, **kw)

            sub.forward = wrapped
            sub._remat_wrapped = True
            n += 1
    return n
