"""Process/world bootstrap.

Replaces the reference's env-var parsing + NCCL-id TCP dance
(imperative/nccl_context.cc:21-49, c_gen_nccl_id_op.cc) with
jax.distributed.initialize: the coordinator handles rendezvous, XLA handles
comm setup over ICI/DCN.
"""
from __future__ import annotations

import os

import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv"]

_initialized = False


def init_parallel_env():
    """reference distributed/parallel.py:32. Under a fleetrun-style launcher
    PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM (or JAX coordinator env) select the
    process identity; single-process multi-device needs no init."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_COORDINATOR",
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    _initialized = True
    return ParallelEnv()


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


class ParallelEnv:
    """reference fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return get_rank()

    @property
    def dev_id(self) -> int:
        return 0

    @property
    def device_count(self) -> int:
        return jax.local_device_count()

    @property
    def current_endpoint(self) -> str:
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self) -> list[str]:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:6170"]
