"""paddle.distribution (reference python/paddle/fluid/layers/
distributions.py: Normal, Uniform, Categorical, MultivariateNormalDiag).

Distributions compose eager Tensor ops, so log_prob/entropy/kl are
tape-differentiable (policy-gradient losses backprop through them);
sampling draws from the framework RNG (RBG by default on TPU).
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "MultivariateNormalDiag", "kl_divergence", "register_kl"]


def _p():
    import paddle_tpu as paddle
    return paddle


def _to_tensor(v, dtype="float32"):
    paddle = _p()
    from ..fluid.dygraph.varbase import Tensor
    if isinstance(v, Tensor):
        return v
    return paddle.to_tensor(np.asarray(v, dtype))


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_tensor(loc)
        self.scale = _to_tensor(scale)

    def sample(self, shape=(), seed=0):
        paddle = _p()
        base_shape = tuple(shape) + tuple(self.loc.shape)
        eps = paddle.randn(list(base_shape))
        return paddle.add(self.loc, paddle.multiply(self.scale, eps))

    def entropy(self):
        paddle = _p()
        # 0.5 + 0.5 log(2 pi) + log sigma
        c = 0.5 + 0.5 * math.log(2 * math.pi)
        return paddle.add(paddle.log(self.scale),
                          paddle.full_like(self.scale, c))

    def log_prob(self, value):
        paddle = _p()
        value = _to_tensor(value)
        var = paddle.multiply(self.scale, self.scale)
        d = paddle.subtract(value, self.loc)
        return paddle.subtract(
            paddle.scale(paddle.divide(paddle.multiply(d, d), var), -0.5),
            paddle.add(paddle.log(self.scale),
                       paddle.full_like(self.scale,
                                        0.5 * math.log(2 * math.pi))))

    def probs(self, value):
        paddle = _p()
        return paddle.exp(self.log_prob(value))

    def kl_divergence(self, other: "Normal"):
        paddle = _p()
        # log(s2/s1) + (s1^2 + (m1-m2)^2) / (2 s2^2) - 1/2
        var1 = paddle.multiply(self.scale, self.scale)
        var2 = paddle.multiply(other.scale, other.scale)
        d = paddle.subtract(self.loc, other.loc)
        t = paddle.divide(paddle.add(var1, paddle.multiply(d, d)),
                          paddle.scale(var2, 2.0))
        return paddle.add(
            paddle.subtract(paddle.log(other.scale),
                            paddle.log(self.scale)),
            paddle.add(t, paddle.full_like(t, -0.5)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _to_tensor(low)
        self.high = _to_tensor(high)

    def sample(self, shape=(), seed=0):
        paddle = _p()
        base_shape = tuple(shape) + tuple(self.low.shape)
        u = paddle.rand(list(base_shape))
        return paddle.add(self.low, paddle.multiply(
            paddle.subtract(self.high, self.low), u))

    def entropy(self):
        paddle = _p()
        return paddle.log(paddle.subtract(self.high, self.low))

    def log_prob(self, value):
        paddle = _p()
        value = _to_tensor(value)
        inside = paddle.logical_and(
            paddle.greater_equal(value, self.low),
            paddle.less_than(value, self.high))
        lp = paddle.scale(paddle.log(
            paddle.subtract(self.high, self.low)), -1.0)
        neg_inf = paddle.full_like(lp, -1e30)
        return paddle.where(inside, lp, neg_inf)

    def probs(self, value):
        paddle = _p()
        return paddle.exp(self.log_prob(value))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _to_tensor(logits)

    def _log_pmf(self):
        paddle = _p()
        import paddle_tpu.nn.functional as F
        return F.log_softmax(self.logits, axis=-1)

    def sample(self, shape=(), seed=0):
        paddle = _p()
        import paddle_tpu.nn.functional as F
        p = F.softmax(self.logits, axis=-1)
        n = int(np.prod(shape)) if shape else 1
        s = paddle.multinomial(p, num_samples=n, replacement=True)
        if shape:  # [batch..., n] -> [*shape, batch...]
            batch = tuple(s.shape[:-1])
            s = paddle.reshape(
                paddle.transpose(
                    paddle.reshape(s, list(batch) + [n]),
                    [len(batch)] + list(range(len(batch)))),
                list(shape) + list(batch))
        return s

    def entropy(self):
        paddle = _p()
        lp = self._log_pmf()
        p = paddle.exp(lp)
        return paddle.scale(paddle.sum(paddle.multiply(p, lp), axis=-1),
                            -1.0)

    def log_prob(self, value):
        paddle = _p()
        lp = self._log_pmf()
        value = _to_tensor(np.asarray(value, "int64"), "int64")
        import paddle_tpu.nn.functional as F
        onehot = F.one_hot(value, lp.shape[-1])
        return paddle.sum(paddle.multiply(lp, onehot), axis=-1)

    def probs(self, value):
        paddle = _p()
        return paddle.exp(self.log_prob(value))

    def kl_divergence(self, other: "Categorical"):
        paddle = _p()
        lp, lq = self._log_pmf(), other._log_pmf()
        p = paddle.exp(lp)
        return paddle.sum(paddle.multiply(p, paddle.subtract(lp, lq)),
                          axis=-1)

class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (reference
    fluid/layers/distributions.py MultivariateNormalDiag — its batch of
    independent Normals with a joint log-prob/entropy/KL)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _to_tensor(loc)            # [..., D]
        self.scale = _to_tensor(scale)        # [..., D] diag stddev

    def _dim(self):
        return int(self.loc.shape[-1])

    def sample(self, shape=(), seed=0):
        paddle = _p()
        base_shape = tuple(shape) + tuple(self.loc.shape)
        eps = paddle.randn(list(base_shape))
        return paddle.add(self.loc, paddle.multiply(self.scale, eps))

    def entropy(self):
        paddle = _p()
        # D/2 (1 + log 2pi) + sum log sigma_i
        c = 0.5 * self._dim() * (1.0 + math.log(2 * math.pi))
        return paddle.add(
            paddle.sum(paddle.log(self.scale), axis=-1),
            paddle.full([1], c))

    def log_prob(self, value):
        paddle = _p()
        value = _to_tensor(value)
        var = paddle.multiply(self.scale, self.scale)
        d = paddle.subtract(value, self.loc)
        quad = paddle.sum(paddle.divide(paddle.multiply(d, d), var),
                          axis=-1)
        logdet = paddle.scale(paddle.sum(paddle.log(self.scale), axis=-1),
                              2.0)
        c = self._dim() * math.log(2 * math.pi)
        return paddle.scale(
            paddle.add(paddle.add(quad, logdet), paddle.full([1], c)),
            -0.5)

    def kl_divergence(self, other: "MultivariateNormalDiag"):
        paddle = _p()
        var1 = paddle.multiply(self.scale, self.scale)
        var2 = paddle.multiply(other.scale, other.scale)
        d = paddle.subtract(self.loc, other.loc)
        tr = paddle.sum(paddle.divide(var1, var2), axis=-1)
        quad = paddle.sum(paddle.divide(paddle.multiply(d, d), var2),
                          axis=-1)
        logdet = paddle.subtract(
            paddle.scale(paddle.sum(paddle.log(other.scale), axis=-1), 2.0),
            paddle.scale(paddle.sum(paddle.log(self.scale), axis=-1), 2.0))
        k = float(self._dim())
        return paddle.scale(
            paddle.add(paddle.add(tr, quad),
                       paddle.subtract(logdet, paddle.full([1], k))),
            0.5)


def kl_divergence(p: Distribution, q: Distribution):
    """paddle.distribution.kl_divergence dispatch (reference
    distribution/kl.py registry — same-type closed forms here)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__}) has "
            f"no closed form registered")
    return p.kl_divergence(q)


def register_kl(cls_p, cls_q):
    """Decorator registering a custom KL (reference register_kl)."""
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


_KL_REGISTRY: dict = {}
