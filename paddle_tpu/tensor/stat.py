"""Statistics APIs (reference python/paddle/tensor/stat.py)."""
from __future__ import annotations

from . import math as m
from ..common_ops import run_op

__all__ = ["mean", "std", "var", "numel", "median"]

mean = m.mean


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    mu = m.mean(x, axis=axis, keepdim=True)
    sq = m.square(m.subtract(x, mu))
    r = m.mean(sq, axis=axis, keepdim=keepdim)
    if unbiased:
        import numpy as np
        shape = x.shape
        if axis is None:
            n = int(np.prod(shape))
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            n = int(np.prod([shape[a] for a in axes]))
        if n > 1:
            r = m.scale(r, scale=n / (n - 1))
    return r


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return m.sqrt(var(x, axis, unbiased, keepdim))


def numel(x, name=None):
    import numpy as np
    from .creation import to_tensor
    return to_tensor(np.asarray(int(np.prod(x.shape)), dtype="int64"))


def median(x, axis=None, keepdim=False, name=None):
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    from ..fluid.framework import in_dygraph_mode
    if in_dygraph_mode():
        return Tensor(jnp.median(x._value, axis=axis, keepdims=keepdim),
                      stop_gradient=True)
    raise NotImplementedError
