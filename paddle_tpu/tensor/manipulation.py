"""Manipulation APIs (reference python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

from ..common_ops import run_op, run_op_multi

__all__ = [
    "reshape", "transpose", "concat", "split", "stack", "unstack", "squeeze",
    "unsqueeze", "flatten", "gather", "gather_nd", "scatter",
    "scatter_nd_add", "slice", "strided_slice", "expand", "expand_as",
    "tile", "flip", "roll", "cast", "chunk", "unbind", "index_select",
    "index_sample", "masked_fill", "where", "broadcast_to", "unique",
]


def reshape(x, shape, name=None):
    return run_op("reshape2", {"X": x}, {"shape": [int(s) for s in shape]},
                  extra_outs=("XShape",))


def transpose(x, perm, name=None):
    return run_op("transpose2", {"X": x}, {"axis": [int(p) for p in perm]},
                  extra_outs=("XShape",))


def concat(x, axis=0, name=None):
    return run_op("concat", {"X": list(x)}, {"axis": int(axis)})


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(num_or_sections, int):
        n, sections = num_or_sections, []
    else:
        n = len(num_or_sections)
        sections = [int(s) for s in num_or_sections]
    res = run_op_multi("split", {"X": x},
                       {"axis": int(axis), "num": 0 if sections else n,
                        "sections": sections}, {"Out": n})
    return res["Out"]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def stack(x, axis=0, name=None):
    return run_op("stack", {"X": list(x)}, {"axis": int(axis)}, out_slot="Y")


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    res = run_op_multi("unstack", {"X": x}, {"axis": int(axis), "num": n},
                       {"Y": n})
    return res["Y"]


def unbind(input, axis=0):
    n = input.shape[axis]
    res = run_op_multi("unbind", {"X": input}, {"axis": int(axis)},
                       {"Out": n})
    return res["Out"]


def squeeze(x, axis=None, name=None):
    axes = [] if axis is None else (
        [int(axis)] if isinstance(axis, int) else [int(a) for a in axis])
    return run_op("squeeze2", {"X": x}, {"axes": axes},
                  extra_outs=("XShape",))


def unsqueeze(x, axis, name=None):
    axes = [int(axis)] if isinstance(axis, int) else [int(a) for a in axis]
    return run_op("unsqueeze2", {"X": x}, {"axes": axes},
                  extra_outs=("XShape",))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return run_op("flatten_contiguous_range", {"X": x},
                  {"start_axis": int(start_axis), "stop_axis": int(stop_axis)},
                  extra_outs=("XShape",))


def gather(x, index, axis=None, name=None):
    return run_op("gather", {"X": x, "Index": index},
                  {"axis": int(axis) if axis is not None else 0})


def gather_nd(x, index, name=None):
    return run_op("gather_nd", {"X": x, "Index": index})


def scatter(x, index, updates, overwrite=True, name=None):
    return run_op("scatter", {"X": x, "Ids": index, "Updates": updates},
                  {"overwrite": overwrite})


def scatter_nd_add(x, index, updates, name=None):
    return run_op("scatter_nd_add",
                  {"X": x, "Index": index, "Updates": updates})


def slice(input, axes, starts, ends):
    return run_op("slice", {"Input": input},
                  {"axes": [int(a) for a in axes],
                   "starts": [int(s) for s in starts],
                   "ends": [int(e) for e in ends],
                   "decrease_axis": [], "infer_flags": [1] * len(axes)})


def strided_slice(x, axes, starts, ends, strides, name=None):
    return run_op("strided_slice", {"Input": x},
                  {"axes": [int(a) for a in axes],
                   "starts": [int(s) for s in starts],
                   "ends": [int(e) for e in ends],
                   "strides": [int(s) for s in strides]})


def expand(x, shape, name=None):
    return run_op("expand_v2", {"X": x}, {"shape": [int(s) for s in shape]})


broadcast_to = expand


def expand_as(x, y, name=None):
    return run_op("expand_as_v2", {"X": x, "target_tensor": y})


def tile(x, repeat_times, name=None):
    return run_op("tile", {"X": x},
                  {"repeat_times": [int(r) for r in repeat_times]})


def flip(x, axis, name=None):
    axes = [int(axis)] if isinstance(axis, int) else [int(a) for a in axis]
    return run_op("flip", {"X": x}, {"axis": axes})


def roll(x, shifts, axis=None, name=None):
    sh = [int(shifts)] if isinstance(shifts, int) else [int(s) for s in shifts]
    ax = [] if axis is None else (
        [int(axis)] if isinstance(axis, int) else [int(a) for a in axis])
    return run_op("roll", {"X": x}, {"shifts": sh, "axis": ax})


def cast(x, dtype):
    from ..fluid import core
    return run_op("cast", {"X": x},
                  {"in_dtype": x.dtype, "out_dtype": core.convert_dtype(dtype)},
                  out_dtype=core.convert_dtype(dtype))


def index_select(x, index, axis=0, name=None):
    return run_op("index_select", {"X": x, "Index": index},
                  {"dim": int(axis)})


def index_sample(x, index):
    return run_op("index_sample", {"X": x, "Index": index})


def masked_fill(x, mask, value, name=None):
    return run_op("masked_fill", {"X": x, "Mask": mask},
                  {"value": float(value)})


def where(condition, x=None, y=None, name=None):
    if x is None or y is None:
        raise NotImplementedError(
            "where(cond) nonzero-style is dynamic-shape; not supported on TPU")
    return run_op("where", {"Condition": condition, "X": x, "Y": y})


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    res = run_op_multi("unique", {"X": x}, {"dtype": dtype},
                       {"Out": 1, "Index": 1})
    outs = [res["Out"][0]]
    if return_inverse:
        outs.append(res["Index"][0])
    return outs[0] if len(outs) == 1 else tuple(outs)
