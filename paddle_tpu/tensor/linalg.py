"""Linear-algebra APIs (reference python/paddle/tensor/linalg.py)."""
from __future__ import annotations

from ..common_ops import run_op
from . import math as m

__all__ = ["matmul", "norm", "dist", "t", "cross", "cholesky", "bmm",
           "histogram", "dot"]

matmul = m.matmul
bmm = m.bmm
dot = m.dot
t = m.t


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro" and axis is None:
        return run_op("frobenius_norm", {"X": x})
    if axis is None:
        return run_op("p_norm", {"X": x},
                      {"porder": float(p), "asvector": True})
    return run_op("p_norm", {"X": x},
                  {"porder": float(p), "axis": int(axis), "keepdim": keepdim})


def dist(x, y, p=2, name=None):
    return norm(m.subtract(x, y), p=p)


def cross(x, y, axis=None, name=None):
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    from ..fluid.framework import in_dygraph_mode
    if in_dygraph_mode():
        return Tensor(jnp.cross(x._value, y._value,
                                axis=axis if axis is not None else -1),
                      stop_gradient=x.stop_gradient and y.stop_gradient)
    raise NotImplementedError


def cholesky(x, upper=False, name=None):
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    from ..fluid.framework import in_dygraph_mode
    if in_dygraph_mode():
        c = jnp.linalg.cholesky(x._value)
        return Tensor(jnp.swapaxes(c, -1, -2) if upper else c,
                      stop_gradient=x.stop_gradient)
    raise NotImplementedError


def histogram(input, bins=100, min=0, max=0, name=None):
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    h, _ = jnp.histogram(input._value.reshape(-1), bins=bins,
                         range=None if min == max == 0 else (min, max))
    return Tensor(h.astype(jnp.int64), stop_gradient=True)
