"""Math APIs (reference python/paddle/tensor/math.py)."""
from __future__ import annotations

from ..common_ops import run_op

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "pow",
    "matmul", "mm", "bmm", "dot", "t", "addmm", "maximum", "minimum",
    "sum", "mean", "max", "min", "prod", "abs", "exp", "log", "log2",
    "log10", "log1p", "sqrt", "rsqrt", "square", "sign", "ceil", "floor",
    "round", "reciprocal", "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "tanh", "erf", "clip", "scale", "cumsum", "kron",
    "sigmoid", "increment", "stanh", "multiplex", "logsumexp", "isfinite",
    "isnan", "isinf", "trace", "all", "any",
]


def _ew(op, x, y, name=None):
    return run_op(op, {"X": x, "Y": y}, {"axis": -1})


def add(x, y, name=None):
    return _ew("elementwise_add", x, y)


def subtract(x, y, name=None):
    return _ew("elementwise_sub", x, y)


def multiply(x, y, name=None):
    return _ew("elementwise_mul", x, y)


def divide(x, y, name=None):
    return _ew("elementwise_div", x, y)


def floor_divide(x, y, name=None):
    return _ew("elementwise_floordiv", x, y)


def mod(x, y, name=None):
    return _ew("elementwise_mod", x, y)


remainder = mod
floor_mod = mod


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return run_op("pow", {"X": x}, {"factor": float(y)})
    return _ew("elementwise_pow", x, y)


def maximum(x, y, name=None):
    return _ew("elementwise_max", x, y)


def minimum(x, y, name=None):
    return _ew("elementwise_min", x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return run_op("matmul_v2", {"X": x, "Y": y},
                  {"trans_x": transpose_x, "trans_y": transpose_y})


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return run_op("bmm", {"X": x, "Y": y})


def dot(x, y, name=None):
    return run_op("dot", {"X": x, "Y": y})


def t(input, name=None):
    ndim = len(input.shape)
    if ndim < 2:
        return input
    return run_op("transpose2", {"X": input}, {"axis": [1, 0]},
                  extra_outs=("XShape",))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return run_op("addmm", {"Input": input, "X": x, "Y": y},
                  {"Alpha": float(alpha), "Beta": float(beta)})


def _reduce(op_type, x, axis=None, keepdim=False):
    if axis is None:
        attrs = {"dim": [0], "keep_dim": keepdim, "reduce_all": True}
    else:
        d = axis if isinstance(axis, (list, tuple)) else [axis]
        attrs = {"dim": [int(a) for a in d], "keep_dim": keepdim,
                 "reduce_all": False}
    return run_op(op_type, {"X": x}, attrs)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    r = _reduce("reduce_sum", x, axis, keepdim)
    if dtype is not None:
        r = r.astype(dtype) if hasattr(r, "astype") else r
    return r


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_mean", x, axis, keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_max", x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_min", x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _reduce("reduce_prod", x, axis, keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_all", x, axis, keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return _reduce("reduce_any", x, axis, keepdim)


def _unary(op_type):
    def fn(x, name=None):
        return run_op(op_type, {"X": x})
    fn.__name__ = op_type
    return fn


abs = _unary("abs")
exp = _unary("exp")
log = _unary("log")
log2 = _unary("log2")
log10 = _unary("log10")
log1p = _unary("log1p")
sqrt = _unary("sqrt")
rsqrt = _unary("rsqrt")
square = _unary("square")
sign = _unary("sign")
ceil = _unary("ceil")
floor = _unary("floor")
round = _unary("round")
reciprocal = _unary("reciprocal")
sin = _unary("sin")
cos = _unary("cos")
tan = _unary("tan")
asin = _unary("asin")
acos = _unary("acos")
atan = _unary("atan")
sinh = _unary("sinh")
cosh = _unary("cosh")
tanh = _unary("tanh")
erf = _unary("erf")
sigmoid = _unary("sigmoid")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return run_op("stanh", {"X": x}, {"scale_a": scale_a, "scale_b": scale_b})


def clip(x, min=None, max=None, name=None):
    return run_op("clip", {"X": x},
                  {"min": float(min) if min is not None else float("-inf"),
                   "max": float(max) if max is not None else float("inf")})


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    return run_op("scale", {"X": x},
                  {"scale": float(scale), "bias": float(bias),
                   "bias_after_scale": bias_after_scale})


def cumsum(x, axis=None, dtype=None, name=None):
    return run_op("cumsum", {"X": x},
                  {"axis": int(axis) if axis is not None else -1,
                   "flatten": axis is None})


def kron(x, y, name=None):
    return run_op("kron", {"X": x, "Y": y})


def increment(x, value=1.0, name=None):
    return run_op("increment", {"X": x}, {"step": float(value)})


def multiplex(inputs, index, name=None):
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    stacked = run_op("stack", {"X": list(inputs)}, {"axis": 0},
                     out_slot="Y")
    return run_op("index_sample_stack_pick", {"X": stacked},
                  {}) if False else _multiplex_impl(inputs, index)


def _multiplex_impl(inputs, index):
    from ..fluid.framework import in_dygraph_mode
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    if in_dygraph_mode():
        idx = index._value.reshape(-1).astype("int32")
        rows = jnp.stack([t._value for t in inputs])  # (k, n, d)
        picked = rows[idx, jnp.arange(rows.shape[1])]
        return Tensor(picked, stop_gradient=all(
            t.stop_gradient for t in inputs))
    raise NotImplementedError("multiplex static mode: use gather compose")


def logsumexp(x, axis=None, keepdim=False, name=None):
    m = max(x, axis=axis, keepdim=True)
    e = exp(subtract(x, m))
    s = sum(e, axis=axis, keepdim=keepdim)
    r = log(s)
    m2 = m if keepdim else _reduce("reduce_max", x, axis, keepdim)
    return add(r, m2)


def isfinite(x, name=None):
    return run_op("isfinite_v2", {"X": x}, stop_gradient=True)


def isnan(x, name=None):
    return run_op("isnan_v2", {"X": x}, stop_gradient=True)


def isinf(x, name=None):
    return run_op("isinf_v2", {"X": x}, stop_gradient=True)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    import jax.numpy as jnp
    from ..fluid.framework import in_dygraph_mode
    from ..fluid.dygraph.varbase import Tensor
    if in_dygraph_mode():
        return Tensor(jnp.trace(x._value, offset, axis1, axis2),
                      stop_gradient=x.stop_gradient)
    raise NotImplementedError
