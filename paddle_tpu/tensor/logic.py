"""Logic / comparison APIs (reference python/paddle/tensor/logic.py)."""
from __future__ import annotations

from ..common_ops import run_op

__all__ = ["equal", "not_equal", "less_than", "less_equal", "greater_than",
           "greater_equal", "logical_and", "logical_or", "logical_not",
           "logical_xor", "equal_all", "allclose", "is_empty"]


def _cmp(op):
    def fn(x, y, name=None):
        return run_op(op, {"X": x, "Y": y}, out_dtype="bool",
                      stop_gradient=True)
    fn.__name__ = op
    return fn


equal = _cmp("equal")
not_equal = _cmp("not_equal")
less_than = _cmp("less_than")
less_equal = _cmp("less_equal")
greater_than = _cmp("greater_than")
greater_equal = _cmp("greater_equal")
logical_and = _cmp("logical_and")
logical_or = _cmp("logical_or")
logical_xor = _cmp("logical_xor")


def logical_not(x, out=None, name=None):
    return run_op("logical_not", {"X": x}, out_dtype="bool",
                  stop_gradient=True)


def equal_all(x, y, name=None):
    from . import math as m
    return m.all(equal(x, y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    import jax.numpy as jnp
    from ..fluid.dygraph.varbase import Tensor
    from ..fluid.framework import in_dygraph_mode
    if in_dygraph_mode():
        return Tensor(jnp.allclose(x._value, y._value, rtol=rtol, atol=atol,
                                   equal_nan=equal_nan), stop_gradient=True)
    raise NotImplementedError


def is_empty(x, name=None):
    import numpy as np
    from ..fluid.dygraph.varbase import Tensor
    return Tensor(np.asarray(int(np.prod(x.shape)) == 0), stop_gradient=True)
