"""paddle.tensor namespace (reference python/paddle/tensor/)."""
from . import creation, linalg, logic, manipulation, math, random, search, stat
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

__all__ = (list(creation.__all__) + list(linalg.__all__) +
           list(logic.__all__) + list(manipulation.__all__) +
           list(math.__all__) + list(random.__all__) +
           list(search.__all__) + list(stat.__all__))
