"""Search / sort APIs (reference python/paddle/tensor/search.py)."""
from __future__ import annotations

from ..common_ops import run_op, run_op_multi

__all__ = ["argmax", "argmin", "argsort", "sort", "topk", "where",
           "index_select", "nonzero", "masked_select"]

from .manipulation import index_select, where  # noqa: F401


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return run_op("arg_max", {"X": x},
                  {"axis": int(axis) if axis is not None else -1,
                   "keepdims": keepdim, "flatten": axis is None,
                   "dtype": dtype}, out_dtype=dtype, stop_gradient=True)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return run_op("arg_min", {"X": x},
                  {"axis": int(axis) if axis is not None else -1,
                   "keepdims": keepdim, "flatten": axis is None,
                   "dtype": dtype}, out_dtype=dtype, stop_gradient=True)


def argsort(x, axis=-1, descending=False, name=None):
    res = run_op_multi("argsort", {"X": x},
                       {"axis": int(axis), "descending": descending},
                       {"Out": 1, "Indices": 1})
    return res["Indices"][0]


def sort(x, axis=-1, descending=False, name=None):
    res = run_op_multi("argsort", {"X": x},
                       {"axis": int(axis), "descending": descending},
                       {"Out": 1, "Indices": 1})
    return res["Out"][0]


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    res = run_op_multi("top_k_v2", {"X": x},
                       {"k": int(k), "axis": int(axis)
                        if axis is not None else -1,
                        "largest": largest, "sorted": sorted},
                       {"Out": 1, "Indices": "int64"})
    return res["Out"][0], res["Indices"][0]


def nonzero(x, as_tuple=False):
    raise NotImplementedError(
        "nonzero produces dynamic shapes; use masks on TPU")


def masked_select(x, mask, name=None):
    raise NotImplementedError(
        "masked_select produces dynamic shapes; use where(mask, x, 0) on TPU")
