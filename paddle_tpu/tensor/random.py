"""Random APIs (reference python/paddle/tensor/random.py)."""
from __future__ import annotations

from ..common_ops import run_op

__all__ = ["normal", "uniform", "randn", "rand", "randint", "randperm",
           "bernoulli", "multinomial", "standard_normal"]


def normal(mean=0.0, std=1.0, shape=None, name=None):
    return run_op("gaussian_random", {},
                  {"shape": [int(s) for s in (shape or [1])],
                   "mean": float(mean), "std": float(std),
                   "dtype": "float32"}, stop_gradient=True)


def standard_normal(shape, dtype="float32", name=None):
    return run_op("gaussian_random", {},
                  {"shape": [int(s) for s in shape], "mean": 0.0, "std": 1.0,
                   "dtype": dtype}, stop_gradient=True)


randn = standard_normal


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    return run_op("uniform_random", {},
                  {"shape": [int(s) for s in shape], "min": float(min),
                   "max": float(max), "seed": seed, "dtype": dtype},
                  stop_gradient=True)


def rand(shape, dtype="float32", name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return run_op("randint", {},
                  {"shape": [int(s) for s in shape], "low": int(low),
                   "high": int(high), "dtype": dtype}, stop_gradient=True)


def randperm(n, dtype="int64", name=None):
    return run_op("randperm", {}, {"n": int(n), "dtype": dtype},
                  stop_gradient=True)


def bernoulli(x, name=None):
    return run_op("bernoulli", {"X": x}, stop_gradient=True)


def multinomial(x, num_samples=1, replacement=False, name=None):
    return run_op("multinomial", {"X": x},
                  {"num_samples": int(num_samples),
                   "replacement": replacement}, stop_gradient=True)
