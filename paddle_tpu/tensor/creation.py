"""Tensor creation APIs (reference python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np

from ..common_ops import run_op
from ..fluid import core
from ..fluid.framework import in_dygraph_mode
from ..fluid.dygraph.varbase import Tensor, to_tensor_value

__all__ = ["to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
           "full_like", "arange", "eye", "linspace", "empty", "empty_like",
           "tril", "triu", "diag", "meshgrid", "assign", "clone"]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        t = Tensor(data._value if dtype is None
                   else data._value.astype(core.convert_dtype(dtype)),
                   stop_gradient=stop_gradient)
        return t
    return Tensor(to_tensor_value(data, dtype), stop_gradient=stop_gradient)


def full(shape, fill_value, dtype=None, name=None):
    dtype = core.convert_dtype(dtype) if dtype else "float32"
    return run_op("fill_constant", {},
                  {"shape": [int(s) for s in shape], "value": float(fill_value),
                   "dtype": dtype}, out_dtype=dtype, stop_gradient=True)


def zeros(shape, dtype=None, name=None):
    return full(shape, 0.0, dtype)


def ones(shape, dtype=None, name=None):
    return full(shape, 1.0, dtype)


def zeros_like(x, dtype=None, name=None):
    return run_op("fill_any_like", {"X": x},
                  {"value": 0.0, "dtype": core.convert_dtype(dtype)
                   if dtype else -1}, stop_gradient=True)


def ones_like(x, dtype=None, name=None):
    return run_op("fill_any_like", {"X": x},
                  {"value": 1.0, "dtype": core.convert_dtype(dtype)
                   if dtype else -1}, stop_gradient=True)


def full_like(x, fill_value, dtype=None, name=None):
    return run_op("fill_any_like", {"X": x},
                  {"value": float(fill_value),
                   "dtype": core.convert_dtype(dtype) if dtype else -1},
                  stop_gradient=True)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    dtype = core.convert_dtype(dtype) if dtype else (
        "int64" if all(isinstance(v, int) for v in (start, end, step))
        else "float32")
    if in_dygraph_mode():
        import jax.numpy as jnp
        return Tensor(jnp.arange(start, end, step,
                                 dtype=np.dtype(dtype)), stop_gradient=True)
    from ..fluid import layers
    return layers.range(start, end, step, dtype)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return run_op("eye", {},
                  {"num_rows": int(num_rows),
                   "num_columns": int(num_columns or -1),
                   "dtype": core.convert_dtype(dtype) if dtype else "float32"},
                  stop_gradient=True)


def linspace(start, stop, num, dtype=None, name=None):
    import jax.numpy as jnp
    dtype = core.convert_dtype(dtype) if dtype else "float32"
    if in_dygraph_mode():
        return Tensor(jnp.linspace(start, stop, int(num),
                                   dtype=np.dtype(dtype)), stop_gradient=True)
    from ..fluid import layers
    s = layers.fill_constant([1], dtype, float(start))
    e = layers.fill_constant([1], dtype, float(stop))
    n = layers.fill_constant([1], "int32", int(num))
    return run_op("linspace", {"Start": s, "Stop": e, "Num": n},
                  {"dtype": dtype}, out_dtype=dtype)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def tril(x, diagonal=0, name=None):
    return run_op("tril_triu", {"X": x},
                  {"diagonal": int(diagonal), "lower": True})


def triu(x, diagonal=0, name=None):
    return run_op("tril_triu", {"X": x},
                  {"diagonal": int(diagonal), "lower": False})


def diag(x, offset=0, padding_value=0, name=None):
    return run_op("diag_v2", {"X": x},
                  {"offset": int(offset),
                   "padding_value": float(padding_value)})


def meshgrid(*args, **kwargs):
    from ..common_ops import run_op_multi
    xs = list(args[0]) if len(args) == 1 and \
        isinstance(args[0], (list, tuple)) else list(args)
    res = run_op_multi("meshgrid", {"X": xs}, {}, {"Out": len(xs)})
    return res["Out"]


def assign(x, output=None):
    if isinstance(x, (np.ndarray, int, float, list, tuple)):
        arr = np.asarray(x)
        if in_dygraph_mode():
            return to_tensor(arr)
        from ..fluid import layers
        return layers.assign(arr, output)
    return run_op("assign", {"X": x})


def clone(x, name=None):
    return run_op("assign", {"X": x})
