"""Dual-mode op invocation for the 2.0 functional API.

In dygraph mode ops execute eagerly through the Tracer (the reference's
generated `core.ops.*` fast path, pybind/op_function_generator.cc); in static
mode they append ops to the current Program via LayerHelper.
"""
from __future__ import annotations

from .fluid import framework
from .fluid.framework import in_dygraph_mode
from .fluid.layer_helper import LayerHelper

__all__ = ["run_op", "run_op_multi"]


def run_op(op_type: str, inputs: dict, attrs: dict | None = None,
           out_slot: str = "Out", out_dtype=None, extra_outs: tuple = (),
           stop_gradient: bool = False):
    """Run/append one op, returning the tensor of `out_slot`.

    extra_outs: additional output slots to allocate (and discard) in static
    mode — e.g. reshape2's XShape.
    """
    attrs = attrs or {}
    if in_dygraph_mode():
        tr = framework._dygraph_tracer()
        res = tr.trace_op(op_type, inputs, {}, attrs,
                          stop_gradient=stop_gradient)
        return res[out_slot][0]
    helper = LayerHelper(op_type)
    dtype = out_dtype
    if dtype is None:
        for lst in inputs.values():
            if lst:
                v0 = lst[0] if isinstance(lst, (list, tuple)) else lst
                dtype = getattr(v0, "dtype", None)
                if dtype:
                    break
    out = helper.create_variable_for_type_inference(dtype or "float32")
    outputs = {out_slot: [out]}
    for slot in extra_outs:
        outputs[slot] = [helper.create_variable_for_type_inference(
            dtype or "float32", True)]
    helper.append_op(type=op_type, inputs=_norm(inputs), outputs=outputs,
                     attrs=attrs)
    return out


def run_op_multi(op_type: str, inputs: dict, attrs: dict | None = None,
                 out_slots: dict | None = None, stop_gradient: bool = False):
    """Run/append one op with several output slots.

    out_slots: slot -> number of outputs (or dtype string for single output).
    Returns dict slot -> list of tensors.
    """
    attrs = attrs or {}
    if in_dygraph_mode():
        tr = framework._dygraph_tracer()
        return tr.trace_op(op_type, inputs, {}, attrs,
                           stop_gradient=stop_gradient)
    helper = LayerHelper(op_type)
    outputs = {}
    for slot, spec in (out_slots or {}).items():
        if isinstance(spec, int):
            outputs[slot] = [helper.create_variable_for_type_inference()
                             for _ in range(spec)]
        else:
            outputs[slot] = [helper.create_variable_for_type_inference(spec)]
    helper.append_op(type=op_type, inputs=_norm(inputs), outputs=outputs,
                     attrs=attrs)
    return outputs


def _norm(inputs: dict) -> dict:
    return {k: (v if isinstance(v, (list, tuple)) else [v])
            for k, v in inputs.items() if v is not None}
