#!/usr/bin/env Rscript
# paddle_tpu inference from R (mirrors reference r/example/mobilenet.r):
# build + save a LeNet from R via reticulate, reload it through the
# Predictor, and compare the ZeroCopy handle path against positional run().

library(reticulate)

python_bin <- Sys.getenv("PADDLE_TPU_PYTHON", unset = "python3")
use_python(python_bin, required = TRUE)

np <- import("numpy")
paddle <- import("paddle_tpu")
inference <- import("paddle_tpu.inference")

model_dir <- file.path(tempdir(), "lenet_r")

save_model <- function() {
    models <- import("paddle_tpu.models.lenet")
    static <- import("paddle_tpu.static")
    model <- models$LeNet()
    model$eval()
    paddle$jit$save(model, model_dir,
                    input_spec = list(static$InputSpec(
                        list(-1L, 1L, 28L, 28L), "float32", "img")))
}

zero_copy_run_lenet <- function() {
    config <- inference$Config(model_dir = model_dir)
    predictor <- inference$Predictor(config)

    img <- np$random$RandomState(0L)$rand(2L, 1L, 28L, 28L)$astype("float32")

    # positional convenience API
    ref <- predictor$run(list(img))[[1]]

    # ZeroCopy handle API: outputs stay device-side until copy_to_cpu
    input_names <- predictor$get_input_names()
    input_tensor <- predictor$get_input_handle(input_names[[1]])
    input_tensor$copy_from_cpu(img)
    predictor$run()
    output_names <- predictor$get_output_names()
    output_tensor <- predictor$get_output_handle(output_names[[1]])
    out <- output_tensor$copy_to_cpu()

    stopifnot(all(dim(out) == dim(ref)))
    stopifnot(max(abs(out - ref)) < 1e-5)
    cat("lenet.r OK: output", paste(dim(out), collapse = "x"),
        "max|zero_copy - positional| =", max(abs(out - ref)), "\n")
}

if (!interactive()) {
    save_model()
    zero_copy_run_lenet()
}
