#!/usr/bin/env python
"""Benchmark driver entry: one JSON line to stdout.

Headline metric (BASELINE config 3): BERT-base pretrain samples/sec/chip —
full MLM+NSP train step (fwd+bwd+AdamW) as ONE jitted XLA computation, bf16
autocast on the MXU. The reference publishes no in-repo numbers
(BASELINE.md), so vs_baseline is the ratio against the north-star A100-MFU
proxy once recorded; 1.0 until then.

Select other configs with BENCH_CONFIG=lenet|bert_base|bert_tiny.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def bench_lenet(batch=256, steps=30, warmup=5):
    import paddle_tpu as paddle
    from paddle_tpu.fluid import Executor, framework, optimizer, unique_name
    from paddle_tpu.fluid.scope import Scope, scope_guard
    from paddle_tpu.models import build_lenet_program

    paddle.enable_static()
    with unique_name.guard():
        main, startup, feeds, fetches = build_lenet_program()
        with framework.program_guard(main, startup):
            opt = optimizer.Adam(learning_rate=1e-3)
            opt.minimize(fetches["loss"])
    scope = Scope()
    with scope_guard(scope):
        exe = Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        img = rng.randn(batch, 1, 28, 28).astype("float32")
        lab = rng.randint(0, 10, (batch, 1)).astype("int64")
        for _ in range(warmup):
            exe.run(main, feed={"img": img, "label": lab},
                    fetch_list=[fetches["loss"]])
        import jax
        t0 = time.perf_counter()
        out = None
        for _ in range(steps):
            out = exe.run(main, feed={"img": img, "label": lab},
                          fetch_list=[fetches["loss"]], return_numpy=False)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
    paddle.disable_static()
    return ("mnist_lenet_static_train_examples_per_sec",
            batch * steps / dt, "examples/sec")


def bench_bert(cfg_name="base", batch=16, seq=128, steps=12, warmup=3):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.jit.functional import make_train_step
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    cfg = BertConfig.base() if cfg_name == "base" else BertConfig.tiny()
    model = BertForPretraining(cfg)
    model.train()

    def loss_fn(m, ids, mlm, nsp):
        logits, nsp_logits = m(ids)
        return m.loss(logits, nsp_logits, mlm, nsp)

    step = make_train_step(model, loss_fn, optimizer="adamw", lr=1e-4,
                           amp_level="O1")
    rng = np.random.RandomState(0)
    ids = rng.randint(4, cfg.vocab_size, (batch, seq)).astype("int64")
    mlm = np.full((batch, seq), -100, "int64")
    mlm[:, ::7] = ids[:, ::7]
    nsp = rng.randint(0, 2, (batch, 1)).astype("int64")
    for _ in range(warmup):
        loss = step(ids, mlm, nsp)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, mlm, nsp)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return (f"bert_{cfg_name}_pretrain_samples_per_sec_per_chip",
            batch * steps / dt, "samples/sec/chip")


def main():
    which = os.environ.get("BENCH_CONFIG", "bert_base")
    if which == "lenet":
        metric, value, unit = bench_lenet()
    elif which == "bert_tiny":
        metric, value, unit = bench_bert("tiny", batch=8, seq=64)
    else:
        metric, value, unit = bench_bert("base")
    print(json.dumps({
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
