#!/usr/bin/env python
"""Benchmark driver entry: one JSON line to stdout.

Round-1 metric: BASELINE config 1 (fluid MNIST LeNet, static ProgramDesc,
single chip) — examples/sec through the full Executor train step (feed,
jitted forward+backward+adam, fetch). The reference publishes no numbers
(BASELINE.md), so vs_baseline is the ratio against the first measured value
recorded here once hardware numbers exist.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def bench_lenet(batch=256, steps=30, warmup=5):
    import paddle_tpu as paddle
    from paddle_tpu.fluid import Executor, framework, optimizer, unique_name
    from paddle_tpu.fluid.scope import Scope, scope_guard
    from paddle_tpu.models import build_lenet_program

    paddle.enable_static()
    with unique_name.guard():
        main, startup, feeds, fetches = build_lenet_program()
        with framework.program_guard(main, startup):
            opt = optimizer.Adam(learning_rate=1e-3)
            opt.minimize(fetches["loss"])
    scope = Scope()
    with scope_guard(scope):
        exe = Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        img = rng.randn(batch, 1, 28, 28).astype("float32")
        lab = rng.randint(0, 10, (batch, 1)).astype("int64")
        for _ in range(warmup):
            exe.run(main, feed={"img": img, "label": lab},
                    fetch_list=[fetches["loss"]])
        import jax
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed={"img": img, "label": lab},
                          fetch_list=[fetches["loss"]], return_numpy=False)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
    paddle.disable_static()
    return batch * steps / dt


def main():
    eps = bench_lenet()
    print(json.dumps({
        "metric": "mnist_lenet_static_train_examples_per_sec",
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
