#!/usr/bin/env python
"""Benchmark driver entry: one JSON line to stdout.

Headline metric (BASELINE config 3): BERT-base pretrain samples/sec/chip —
full MLM+NSP train step (fwd+bwd+AdamW) as ONE jitted XLA computation, bf16
autocast on the MXU, Pallas flash attention + fused layer_norm on the hot
path, hardware-RBG PRNG for dropout (threefry cost ~30% of the step; see
paddle_tpu/__init__). MFU is computed from analytic model FLOPs
(matmul-only, fwd+2×bwd) against the chip's peak bf16 FLOP/s — peak is
resolved from the device kind with a TPU_PEAK_TFLOPS_BF16 env override, and
the assumption is printed so the number is auditable.

Round-3 measured (v5e single chip): bert_base b64 s128 = 916 samples/s,
32.5% MFU; bert_base_512 b16 = 234 samples/s, 35.8% MFU (r2: 519 / 22.5%);
gpt-350M s1024 = 33.7k tokens/s, 41.5% MFU (flash attention + per-layer
remat); resnet50 = 1548 images/s. The +21% over the earlier 759 samples/s
comes from the masked-positions MLM head (only the ~15% predicted rows hit
the 30k-vocab projection, MLPerf practice; MFU accounts the REDUCED
flops). Binding-constraint analysis: step is HBM-bandwidth-bound —
XLA-counted bytes 60GB/step = ~680 GB/s sustained (~83% of v5e peak BW)
while XLA-counted FLOPs match analytic model FLOPs (no wasted compute);
marginal GEMM rate 162 TFLOP/s (82% of peak) at BERT shapes; flash
attention beats XLA sdpa 1.4x in-step (block 512 optimal at s512); amp O2
gains <3% over O1; further MFU needs fusing the LN/gelu/bias/dropout
chains (fewer materialised activations), not more matmul tuning.

The reference publishes no in-repo numbers (BASELINE.md), so vs_baseline is
1.0 until a measured reference lands.

Configs (BENCH_CONFIG=...): bert_base (default, seq 128; also records the
secondary configs in an "extras" dict unless BENCH_EXTRAS=0) | bert_base_512
| bert_tiny | lenet | gpt (350M tokens/sec) | resnet50 | widedeep |
infer (BERT predictor latency) | flash_attn (pallas-vs-jnp microbench) |
allreduce | metrics_overhead (telemetry enabled-vs-disabled decode
step-time delta, <2% bar) | flight_overhead (flight recorder only
toggled, same harness and bar) | perfwatch_overhead (perf-plane step
sampler at its default cadence vs off, same harness and bar) |
checkpoint (store save/restore MB/s,
dedup ratio on a 1%-mutated state, async-vs-sync save step overhead,
<5% bar) | slo (open-loop traffic replay against the serving tier:
SLO attainment, goodput, p99 TTFT/ITL) | prefix (shared-prefix radix
KV cache A/B, cache on vs off on a system-prompt + unique-suffix mix:
goodput tokens/s, p99 TTFT, prefill-FLOPs reduction and the measured
effective-KV-capacity multiplier) | chaos (same seeded traffic +
a serving_decode stall mid-run: watchdog detection + recovery seconds
and post-recovery SLO delta vs the fault-free baseline) | router
(replicated fleet behind the fault-tolerant router: one replica killed
mid-run under wire traffic — failover detect + respawn recovery
seconds, post-recovery attainment delta, wire TTFT via streaming) |
kernels (per-kernel fused-vs-unfused speedups for the epilogue-fused
decoder sub-blocks + autobench tuning-cache cold/warm first-call
latency) | transport (multiplexed RPC A/B: wire TTFT p50/p99 through
ONE shared client under a concurrency sweep of long streams, mux vs
legacy one-call-per-channel, plus the zero-copy pull path's
bytes-copied-per-payload-byte on both paths) | online (continuous
publish pipeline: PS push -> servable-version staleness on the wire,
streamed-generate max inter-token gap across a staggered 2-replica
rollout vs steady-state ITL, cross-version chunk dedup ratio on a
one-row-mutated embedding) | ps_ha (PS high-availability plane:
kill-primary -> promoted-standby first-push wall time vs the pre-HA
snapshot-respawn baseline, semi-sync vs async push-ack tax, and
steady-state replication lag under a wide&deep-style push stream) |
tsdb (time-series plane: collector TSDB + alert evaluator toggled
A/B/A behind a live agent, same <2% decode bar, plus the store's own
ingest rate, bytes/sample after downsampling, and range/rate/quantile
query latency).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_T0 = time.perf_counter()



def _sync(x):
    """True device sync. jax.block_until_ready can return at ENQUEUE time
    through the axon tunnel (measured: 53 PFLOP/s 'sustained' without this),
    so every timed region must end with an actual value fetch. The fetch
    must be TINY: the tunnel moves D2H at ~8 MB/s, so materializing a whole
    logits tensor times the transport, not the model — slice one element
    on device and fetch 4 bytes (one relay round-trip)."""
    arr = x
    while isinstance(arr, (list, tuple)):
        arr = arr[0]
    if hasattr(arr, "addressable_shards"):  # device-side jax array
        import jax.numpy as jnp
        arr = jnp.ravel(arr)[:1]
    return np.asarray(arr).ravel()[:1]


def _finish_timed(t0, loss):
    """Close a timed loop started at t0: sync on `loss`, then measure one
    idle sync (pure tunnel RTT, see README runtime notes) and charge it
    once rather than once-per-step. Floor at half the raw loop time so a
    mismeasured RTT can never halve a real result."""
    _sync(loss)
    loop = time.perf_counter() - t0
    t1 = time.perf_counter()
    _sync(loss)
    return max(loop - (time.perf_counter() - t1), loop * 0.5)


def chip_peak_flops():
    # the peak table lives in the perf plane (ONE source for the live
    # MFU gauges and the bench reports — the two can never disagree)
    from paddle_tpu.observability import perf as _perf
    peak, kind = _perf.chip_peak_flops()
    if os.environ.get("TPU_PEAK_TFLOPS_BF16"):
        return peak, "env"
    if not any(sub in kind.lower() for sub, _ in _perf._PEAKS):
        return peak, f"{kind or 'unknown'} (assumed v4-class)"
    return peak, kind


def bert_train_flops_per_step(cfg, batch, seq, n_pred=None):
    """Analytic matmul FLOPs for one train step (fwd + 2x for bwd).

    Counts the dense projections, attention score/context matmuls, the MLM
    transform + vocab projection and the NSP head; elementwise/norm
    FLOPs are ignored (MFU convention). n_pred = masked positions per
    sequence actually projected into the vocab (None = all `seq`
    positions — the naive head)."""
    H, L, V = cfg.hidden_size, cfg.num_hidden_layers, cfg.vocab_size
    I = cfg.intermediate_size
    tokens = batch * seq
    per_layer = (
        2 * H * (3 * H)          # qkv proj
        + 2 * H * H              # attention out proj
        + 2 * 2 * seq * H        # scores QK^T + context PV (per token)
        + 2 * H * I + 2 * I * H  # ffn up + down
    )
    pred_tokens = batch * (n_pred if n_pred is not None else seq)
    mlm_head = 2 * H * H + 2 * H * V    # transform + vocab proj
    fwd = tokens * L * per_layer + pred_tokens * mlm_head \
        + batch * (2 * H * 2)
    return 3 * fwd  # fwd + bwd(≈2x fwd)


def bench_lenet(batch=256, steps=30, warmup=5):
    import paddle_tpu as paddle
    from paddle_tpu.fluid import Executor, framework, optimizer, unique_name
    from paddle_tpu.fluid.scope import Scope, scope_guard
    from paddle_tpu.models import build_lenet_program

    paddle.enable_static()
    with unique_name.guard():
        main, startup, feeds, fetches = build_lenet_program()
        with framework.program_guard(main, startup):
            opt = optimizer.Adam(learning_rate=1e-3)
            opt.minimize(fetches["loss"])
    scope = Scope()
    with scope_guard(scope):
        exe = Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        img = rng.randn(batch, 1, 28, 28).astype("float32")
        lab = rng.randint(0, 10, (batch, 1)).astype("int64")
        for _ in range(warmup):
            exe.run(main, feed={"img": img, "label": lab},
                    fetch_list=[fetches["loss"]])
        _sync(out := exe.run(main, feed={"img": img, "label": lab},
                             fetch_list=[fetches["loss"]],
                             return_numpy=False))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed={"img": img, "label": lab},
                          fetch_list=[fetches["loss"]], return_numpy=False)
        _sync(out)
        dt = time.perf_counter() - t0
    paddle.disable_static()
    return {"metric": "mnist_lenet_static_train_examples_per_sec",
            "value": round(batch * steps / dt, 2), "unit": "examples/sec"}


def bench_bert(cfg_name="base", batch=16, seq=128, steps=32, warmup=3):
    """BERT pretrain step (BASELINE config 3).

    r04 bandwidth profile (v5e, batch 64, s128, measured 2026-07-30):
    the compiled step accesses ~48.7 GB per step (XLA cost analysis); at
    the chip's 819 GB/s that is a ~59 ms bandwidth floor against a
    ~70 ms measured step — the program runs at ~85% of its own floor,
    which caps MFU at ~38-39% for this op structure. Experiments that
    did NOT move the number (all within run-to-run variance of the
    shared tunnel chip, ±5%): layer_norm/softmax off the f32 AMP
    blacklist (the Pallas LN/flash kernels already keep their f32 math
    internal), batch 128. The attention path already runs the Pallas
    flash kernel fwd+bwd; dropout+residual+LN runs the fused Pallas
    epilogue.

    r05 activation-traffic audit (xplane device trace, b64 s128): the
    largest non-matmul cost is the FFN gelu tier — 12 fwd
    `select_convert_fusion`s (erf gelu + saved branch predicate over
    bf16[64,128,3072]) + 12 bwd partners at ~0.51 ms each ≈ 12 ms of the
    ~64 ms step (19%). These passes run ~5x above their bandwidth floor,
    i.e. they are VPU-compute-bound on the erf polynomial, not HBM-bound;
    notably the f32-erf lowering measured FASTER than bf16-erf (which
    up-converts with extra selects), so the existing AMP placement is
    already the fast variant. The FFN pair IS now fused into one Pallas
    kernel (ops/pallas_ffn.py: poly-erf gelu computed in VMEM, 4H
    intermediate never reaches HBM, bwd rematerialises) wired through
    nn.TransformerEncoderLayer. In isolation the kernel beats the XLA
    chain 1.35x fwd / 1.23x fwd+bwd at BERT shapes (70 vs 52 TF/s fwd);
    at FULL-STEP granularity a same-process A/B measured ~1.00x
    (65.5-66.7 ms both ways, 3 reps) — XLA's schedule already overlaps
    the gelu tier with neighboring work, so removing it does not
    shorten the critical path. The fused path stays on (never slower,
    structurally less HBM traffic, guaranteed-fusion contract), and the
    r04 ~39% structural cap stands. With the RTT-clean timing
    convention the step measures ~980-1000 samples/s = 34.7-35.4%
    MFU."""
    import jax
    from paddle_tpu.jit.functional import make_train_step
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    cfg = BertConfig.base() if cfg_name.startswith("base") \
        else BertConfig.tiny()
    model = BertForPretraining(cfg)
    model.train()

    # MLPerf-BERT convention: only max_predictions_per_seq (~15%) masked
    # positions reach the vocab projection (models/bert.py
    # masked_positions path)
    n_pred = min(seq, max(8, int(round(seq * 0.15))))

    def loss_fn(m, ids, pos, mlm, nsp):
        logits, nsp_logits = m(ids, masked_positions=pos)
        return m.loss(logits, nsp_logits, mlm, nsp)

    step = make_train_step(model, loss_fn, optimizer="adamw", lr=1e-4,
                           amp_level="O1")
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    ids_np = rng.randint(4, cfg.vocab_size, (batch, seq)).astype("int64")
    pos_np = np.stack([
        np.sort(rng.choice(seq, n_pred, replace=False))
        for _ in range(batch)]).astype("int64")
    mlm_np = np.take_along_axis(ids_np, pos_np, axis=1)
    ids = jnp.asarray(ids_np)
    pos = jnp.asarray(pos_np)
    mlm = jnp.asarray(mlm_np)
    nsp = jnp.asarray(rng.randint(0, 2, (batch, 1)).astype("int64"))
    jax.block_until_ready([ids, pos, mlm, nsp])
    for _ in range(warmup):
        loss = step(ids, pos, mlm, nsp)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, pos, mlm, nsp)
    dt = _finish_timed(t0, loss)

    samples_sec = batch * steps / dt
    flops_step = bert_train_flops_per_step(cfg, batch, seq, n_pred)
    peak, kind = chip_peak_flops()
    mfu = flops_step * steps / dt / peak
    suffix = f"_{seq}" if seq != 128 else ""
    return {"metric": f"bert_{cfg_name.split('_')[0]}{suffix}"
                      "_pretrain_samples_per_sec_per_chip",
            "value": round(samples_sec, 2), "unit": "samples/sec/chip",
            "mfu": round(mfu, 4), "model_flops_per_step": flops_step,
            "peak_flops_assumed": peak, "device_kind": str(kind),
            "batch": batch, "seq": seq}


def bench_flash_attn(steps=20, warmup=3):
    """Pallas flash attention vs jnp sdpa at BERT-base seq-512 shapes
    (fwd+bwd). The 'value' is the pallas step speedup over jnp."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.flash_attention import sdpa_reference
    from paddle_tpu.ops.pallas_attention import can_use_flash, flash_attention

    B, H, S, D = 16, 12, 512, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32),
                    dtype=jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32),
                    dtype=jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32),
                    dtype=jnp.bfloat16)
    assert can_use_flash(q, k, v, None)

    def time_fn(f):
        # repeat inside ONE jit via scan: the axon tunnel re-uploads inputs
        # on every dispatch (~23 ms for these shapes), which would swamp the
        # kernel comparison
        rep = 8
        grad = jax.grad(lambda q, k, v: jnp.sum(
            f(q, k, v).astype(jnp.float32) ** 2), argnums=(0, 1, 2))

        @jax.jit
        def loop(q, k, v):
            def body(c, _):
                dq, dk, dv = grad(c[0], c[1], c[2])
                return (dq * 1e-6 + q, dk * 1e-6 + k, dv * 1e-6 + v), None
            c, _ = jax.lax.scan(body, (q, k, v), None, length=rep)
            return c

        out = loop(q, k, v)
        _sync(out[0])
        t0 = time.perf_counter()
        for _ in range(max(steps // rep, 2)):
            out = loop(*out)
        _sync(out[0])
        return (time.perf_counter() - t0) / (max(steps // rep, 2) * rep)

    t_pallas = time_fn(lambda q, k, v: flash_attention(q, k, v))
    t_jnp = time_fn(lambda q, k, v: sdpa_reference(q, k, v))
    return {"metric": "flash_attention_seq512_speedup_vs_jnp",
            "value": round(t_jnp / t_pallas, 3), "unit": "x",
            "pallas_ms": round(t_pallas * 1e3, 3),
            "jnp_ms": round(t_jnp * 1e3, 3)}


def gpt_train_flops_per_step(cfg, batch, seq):
    """Matmul-only analytic FLOPs, fwd + 2x bwd (MFU convention)."""
    H, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    F = cfg.intermediate_size
    tokens = batch * seq
    per_layer = (3 * 2 * H * H      # q, k, v projections
                 + 2 * H * H        # out projection
                 + 2 * 2 * seq * H  # scores + context (per token)
                 + 2 * H * F + 2 * F * H)
    fwd = tokens * (L * per_layer + 2 * H * V)
    return 3 * fwd


def bench_gpt(batch=8, seq=1024, steps=10, warmup=2, dp=1, pp=1, tp=1):
    """GPT-350M causal-LM train step (BASELINE config 5 single-chip proxy;
    the full dp x pp x tp path is validated by dryrun_multichip and scales
    via the same HybridParallelTrainStep)."""
    import jax
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.parallel.hybrid import HybridParallelTrainStep

    from paddle_tpu.ops.pallas_attention import on_tpu
    cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                    max_position_embeddings=max(1024, seq),
                    amp_dtype="bfloat16",
                    attn_impl="flash" if on_tpu() else "xla")
    step = HybridParallelTrainStep(cfg, dp=dp, pp=pp, tp=tp,
                                   n_microbatches=2 * pp if pp > 1 else None,
                                   grad_clip_norm=1.0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    for _ in range(warmup):
        loss = step(ids)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids)
    dt = _finish_timed(t0, loss)
    toks = batch * seq * steps / dt
    peak, kind = chip_peak_flops()
    mfu = gpt_train_flops_per_step(cfg, batch, seq) * steps / dt / peak
    return {"metric": "gpt_350m_train_tokens_per_sec_per_chip",
            "value": round(toks, 1), "unit": "tokens/sec/chip",
            "mfu": round(mfu, 4), "batch": batch, "seq": seq,
            "dp": dp, "pp": pp, "tp": tp, "device_kind": str(kind)}


def bench_gpt_1p3b(batch=1, seq=1024, steps=4, warmup=1):
    """GPT-3 XL (1.3B params) with per-block remat, ONE chip (the round-4
    verdict's missing entry). Memory math first: AdamW keeps f32 params +
    m1 + m2 = 3 x 5.3 GB = 16.0 GB for 1.33B params before grads or
    activations — against v5e's 16 GB HBM this cannot fit even at
    batch 1 with remat, so the expected record is the documented-
    impossible entry with the allocator's own numbers. The 2-way pp or tp
    split that WOULD fit (8 GB of optimizer state per chip) needs 2
    physical chips; this environment exposes one (dryrun_multichip
    validates those meshes on virtual devices instead)."""
    import jax
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.parallel.hybrid import HybridParallelTrainStep
    from paddle_tpu.ops.pallas_attention import on_tpu

    cfg = GPTConfig.gpt3_1p3b(amp_dtype="bfloat16",
                              attn_impl="flash" if on_tpu() else "xla",
                              remat=True)
    D, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    n_params = V * D + cfg.max_position_embeddings * D + 2 * D \
        + L * (12 * D * D + 13 * D)
    base = {"metric": "gpt_1p3b_train_tokens_per_sec_per_chip",
            "unit": "tokens/sec/chip", "batch": batch, "seq": seq,
            "n_params": n_params, "remat": True}
    # memory precheck BEFORE paying the (large, doomed) compile: f32
    # params + AdamW m1/m2 + bf16 grads; v5e HBM = 16 GiB. Verified by
    # attempting the real compile once in r05: RESOURCE_EXHAUSTED.
    hbm_gib = float(os.environ.get("TPU_HBM_GIB", 16))
    need_gib = n_params * (3 * 4 + 2) / 2**30
    if need_gib > hbm_gib * 0.95:
        base.update(
            value=None,
            impossible_on_1_chip=(
                f"f32 AdamW master+moments + bf16 grads = {need_gib:.1f} "
                f"GiB vs {hbm_gib:.0f} GiB HBM; fits under pp=2 or tp=2 "
                "(needs 2 physical chips, not available here; "
                "dp2xpp2xtp2 compiles+runs in dryrun_multichip)"))
        return base
    try:
        step = HybridParallelTrainStep(cfg, dp=1, pp=1, tp=1,
                                       grad_clip_norm=1.0)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        for _ in range(warmup):
            loss = step(ids)
        _sync(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids)
        dt = _finish_timed(t0, loss)
        peak, kind = chip_peak_flops()
        mfu = gpt_train_flops_per_step(cfg, batch, seq) * steps / dt / peak
        base.update(value=round(batch * seq * steps / dt, 1),
                    mfu=round(mfu, 4), device_kind=str(kind))
        return base
    except Exception as e:
        msg = str(e)
        base.update(
            value=None,
            impossible_on_1_chip=(
                "f32 AdamW master+moments alone = "
                f"{3 * n_params * 4 / 2**30:.1f} GiB vs 16 GiB v5e HBM; "
                "fits under pp=2 or tp=2 (needs 2 physical chips, not "
                "available here; dp2xpp2xtp2 compiles+runs in "
                "dryrun_multichip)"),
            error=f"{type(e).__name__}: {msg[:180]}")
        return base


def resnet_train_flops_per_step(batch):
    """ResNet-50 224x224 forward = 8.18 GFLOP/image (2 x 4.09 GMACs,
    derived per-layer below); train step = fwd + dX + dW = 3x forward.

    CORRECTION (r05): rounds 3-4 used 4.1e9 here, mislabelled "2x MACs" —
    4.09G is ResNet-50's MAC count (the number torchvision quotes as
    "GFLOPS"), so every prior-round resnet MFU was UNDERSTATED 2x. The
    chip peak (197 TF/s bf16) counts an FMA as 2 flops; the model count
    must too, and the BERT/GPT entries already do (2*params*tokens).
    """
    blocks = [(3, 64), (4, 128), (6, 256), (3, 512)]
    f = 2 * 7 * 7 * 3 * 64 * 112 * 112          # stem
    cin, hw = 64, 56 * 56
    for si, (n, cmid) in enumerate(blocks):
        cout = cmid * 4
        for bi in range(n):
            stride = 2 if (bi == 0 and si > 0) else 1
            hw2 = hw // (stride * stride)
            f += 2 * cin * cmid * hw            # 1x1 reduce
            f += 2 * 9 * cmid * cmid * hw2      # 3x3
            f += 2 * cmid * cout * hw2          # 1x1 expand
            if bi == 0:
                f += 2 * cin * cout * hw2       # downsample shortcut
            cin, hw = cout, hw2
    f += 2 * 2048 * 1000                        # fc
    return 3 * f * batch


def bench_resnet50(batch=256, steps=12, warmup=3):
    """ResNet-50 ImageNet train step (BASELINE config 2), bf16 autocast.

    NHWC trunk (channel-minor, the native TPU conv layout; one transpose
    at the stem), bf16 BN IO with f32 statistics (custom-VJP batch_norm).

    Measured profile (r05, v5e, xplane device trace of the compiled step,
    scripts/resnet_decompose.py): device-busy 100.1 ms at b256 =
    **conv-containing fusions 79%** (XLA fuses the BN statistics
    reductions INTO the convolutions — the `convert_reduce_fusion`s that
    dominate the timeline each contain a convolution), BN-normalize/relu/
    residual elementwise passes ~15%, copies ~4%, maxpool-bwd ~2%. The
    convolutions sustain ~43% MXU efficiency — the v5e conv lowering's
    rate at these shapes (K=64..576 contractions, stride-2 layers) — so
    the step is CONV-COMPUTE-bound, not HBM-bound. This retracts r04's
    46.7 GB/step bandwidth-floor profile: that estimate double-counted
    logical passes XLA had already fused away (a 46.7 GB step at the
    measured 100 ms would imply 467 GB/s, 57% of peak, not 99%). The
    remaining headroom (elementwise+copies ~19%) bounds any further BN
    fusion win; a hand-written conv would have to beat XLA's own conv to
    move the 79%.

    r04's recorded 1871 img/s was depressed ~15% by measurement, not
    compute: _sync then fetched a full array (tunnel RTT + transfer
    amortized over 10 steps) and the entry ran late in a long bench
    process. This round's number uses the tiny-slice _sync with the idle
    RTT measured and charged once (the infer-latency convention)."""
    import jax
    from paddle_tpu.jit.functional import make_train_step
    from paddle_tpu.vision.models import resnet50
    import paddle_tpu.nn.functional as F

    model = resnet50(num_classes=1000, data_format="NHWC")
    model.train()

    def loss_fn(m, img, label):
        logits = m(img)
        return F.cross_entropy(logits, label)

    step = make_train_step(model, loss_fn, optimizer="momentum", lr=0.1,
                           amp_level="O1")
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    # device-resident batch: measures the train step, not the 38 MB/step
    # host upload (a real input pipeline prefetches to device)
    img = jnp.asarray(rng.randn(batch, 3, 224, 224).astype("float32"))
    lab = jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64"))
    jax.block_until_ready([img, lab])
    for _ in range(warmup):
        loss = step(img, lab)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(img, lab)
    dt = _finish_timed(t0, loss)
    peak, kind = chip_peak_flops()
    mfu = resnet_train_flops_per_step(batch) * steps / dt / peak
    return {"metric": "resnet50_train_images_per_sec",
            "value": round(batch * steps / dt, 2), "unit": "images/sec",
            "mfu": round(mfu, 4), "batch": batch, "device_kind": str(kind)}


def bench_widedeep_ps_tcp(steps=10, warmup=2, batch=4096, workers=2,
                          servers=2, mode=None):
    """wide&deep through the REAL PS transport (r04 weak #8): `servers`
    PSServer processes + `workers` DownpourWorker processes over
    localhost TCP, reporting aggregate ex/s and the measured pull/push
    wire bytes (PSClient byte counters). mode="boxps" runs the same job
    through the BoxPS-style hot-row cache (boxps_cache.py) — the
    follow-on perf lever of r04 missing #2."""
    import json
    import os as _os
    import socket as _socket
    import subprocess
    import sys as _sys

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    script = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                           "scripts", "widedeep_ps_bench.py")
    eps = [f"127.0.0.1:{free_port()}" for _ in range(servers)]
    env0 = dict(_os.environ)
    env0["PYTHONPATH"] = _os.path.dirname(_os.path.abspath(__file__))
    env0["PS_ENDPOINTS"] = ",".join(eps)
    procs = []
    for ep in eps:
        env = dict(env0)
        env.update(ROLE="server", MY_ENDPOINT=ep)
        procs.append(subprocess.Popen(
            [_sys.executable, script], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
    wps = []
    for wid in range(workers):
        env = dict(env0)
        env.update(ROLE="worker", WORKER_ID=str(wid), STEPS=str(steps),
                   WARMUP=str(warmup), BATCH=str(batch))
        if mode:
            env["MODE"] = mode
        wps.append(subprocess.Popen(
            [_sys.executable, script], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for pr in wps:
            out, err = pr.communicate(timeout=420)
            if pr.returncode != 0:
                return {"error": err[-400:]}
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for pr in procs + wps:   # reap workers too on error/timeout
            pr.terminate()
            try:
                pr.wait(timeout=10)
            except Exception:
                pr.kill()
    rec = {"transport": "tcp_ps" + (f"+{mode}" if mode else ""),
           "servers": servers, "workers": workers, "batch": batch,
           "examples_per_sec": round(sum(
               o["examples_per_sec"] for o in outs), 1),
           "wire_mb_out_per_worker_step": round(np.mean(
               [o["push_pull_mb_out"] / o["steps"] for o in outs]), 2),
           "wire_mb_in_per_worker_step": round(np.mean(
               [o["push_pull_mb_in"] / o["steps"] for o in outs]), 2)}
    return rec


def bench_widedeep(batch=4096, steps=20, warmup=3):
    """wide&deep CTR train step (BASELINE config 4), two paths:

    headline `value` — the TPU-native mesh path (WideDeepTrainStep:
    embedding tables sharded over the device mesh, XLA collective
    lookup; on one chip dp=mp=1 everything is in-HBM compute, no PS).

    `ps_tcp` / `ps_tcp_boxps` — the CTR-production path over the REAL
    transport: PS shards + Downpour workers on TCP (ex/s + measured
    wire bytes), and the same through the BoxPS-style hot-row cache
    (aggregated deltas every flush interval -> ~flush_every x less wire
    traffic)."""
    from paddle_tpu.models.wide_deep import WideDeepConfig, WideDeepTrainStep

    cfg = WideDeepConfig()  # 1M hashed vocab, 26 slots, 13 dense
    step = WideDeepTrainStep(cfg, dp=1, mp=1)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, cfg.num_slots))
    dense = rng.randn(batch, cfg.dense_dim).astype(np.float32)
    label = (ids[:, 0] % 2).astype(np.float32)[:, None]
    for _ in range(warmup):
        loss = step(ids, dense, label)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, dense, label)
    dt = _finish_timed(t0, loss)
    rec = {"metric": "widedeep_train_examples_per_sec",
           "value": round(batch * steps / dt, 1), "unit": "examples/sec",
           "transport": "mesh (in-HBM, XLA collective lookup)",
           "batch": batch, "vocab": cfg.vocab_size,
           "slots": cfg.num_slots}
    mode = os.environ.get("BENCH_WIDEDEEP_PS", "1")
    if mode == "min":
        # reduced budget: one small run through the real transport so the
        # record always carries the TCP numbers (r04 weak #8)
        rec["ps_tcp"] = bench_widedeep_ps_tcp(steps=4, warmup=1)
    elif mode != "0":
        rec["ps_tcp"] = bench_widedeep_ps_tcp(steps=8, warmup=1)
        rec["ps_tcp_boxps"] = bench_widedeep_ps_tcp(steps=8, warmup=1,
                                                    mode="boxps")
    return rec


def bench_serving(num_requests=48, num_slots=8, hidden=512, layers=8,
                  heads=8, max_new=64, seed=0):
    """Offline serving throughput through paddle_tpu.serving: a fixed
    request mix (prompt lens 16..192, outputs 16..max_new) continuously
    batched over the paged KV cache. Reports end-to-end tokens/sec
    (prefill+decode, compile EXCLUDED via a warmup mix that touches
    every bucket), p50/p99 request latency at that offered load, page
    occupancy and the compile-per-bucket counters."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import Engine, GPTDecodeModel

    cfg = GPTConfig(hidden_size=hidden, num_layers=layers, num_heads=heads,
                    max_position_embeddings=512, vocab_size=8192)
    model = GPTDecodeModel(cfg, seed=seed)
    eng = Engine(model, num_slots=num_slots, num_pages=256, page_size=16,
                 max_seq_len=448)
    rng = np.random.RandomState(seed)

    def mix(n):
        out = []
        for _ in range(n):
            plen = int(rng.choice([16, 31, 64, 100, 128, 192]))
            mnt = int(rng.choice([16, 32, max_new]))
            out.append((rng.randint(0, cfg.vocab_size, (plen,)), mnt))
        return out

    # warmup: one prompt per length choice so EVERY prefill bucket (and
    # the decode program) compiles before the timed window — a random
    # warmup mix can miss a bucket and charge its XLA compile to the
    # measurement
    for plen in (16, 31, 64, 100, 128, 192):
        eng.submit(rng.randint(0, cfg.vocab_size, (plen,)), 16)
    eng.run_until_idle()
    reqs = [eng.submit(p, m) for p, m in mix(num_requests)]
    t0 = time.perf_counter()
    eng.run_until_idle()
    dt = time.perf_counter() - t0
    ntok = sum(len(r.generated) for r in reqs)
    lats = sorted(r.latency() for r in reqs)
    st = eng.stats()
    return {"metric": "serving_decode_tokens_per_sec",
            "value": round(ntok / dt, 1), "unit": "tokens/sec",
            "requests": num_requests, "slots": num_slots,
            "model": f"gpt-h{hidden}-l{layers}",
            "p50_ms": round(lats[len(lats) // 2] * 1e3, 1),
            "p99_ms": round(lats[min(len(lats) - 1,
                                     int(0.99 * len(lats)))] * 1e3, 1),
            "compiles": st["compiles"],
            "preemptions": st["preemptions"],
            "pool_pages": st["pool"]["num_pages"]}


def _slo_engine(hidden=256, layers=4, heads=4, num_slots=8, seed=0):
    """Small serving engine, every prefill bucket + the decode program
    pre-compiled (compiles must never land inside an SLO window)."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import Engine, GPTDecodeModel

    cfg = GPTConfig(hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=256,
                    vocab_size=4096)
    eng = Engine(GPTDecodeModel(cfg, seed=seed), num_slots=num_slots,
                 num_pages=128, page_size=8, max_seq_len=96)
    for plen in (4, 8, 16, 32):
        eng.submit(np.full((plen,), 1, np.int32), 2)
    eng.run_until_idle()
    return eng


def _slo_traffic(duration, rate, seed):
    from paddle_tpu.serving import TrafficConfig
    return TrafficConfig(
        rate=rate, duration=duration, arrival="diurnal",
        diurnal_period=duration, seed=seed,
        prompt_lens={4: 3, 8: 3, 16: 2, 32: 1},
        output_lens={4: 3, 8: 2, 16: 1},
        tenants={"web": 3, "batch": 1}, tiers={0: 1, 1: 2, 2: 1},
        deadlines={0: 10.0, 1: 20.0, 2: None}, vocab_size=512)


def bench_transport(concurrencies=(1, 4, 8), probes=30, seed=0):
    """BENCH_CONFIG=transport (docs/PS_WIRE_PROTOCOL.md mux framing):
    the multiplexed transport's reason to exist, measured. ONE shared
    RpcClient carries N long streamed generates while short streamed
    probes measure wire TTFT (time to FIRST frame — queueing included);
    the sweep repeats with mux=False (exclusive one-call-per-channel
    legacy mode), which reproduces the PR-9 head-of-line symptom.
    Also reports the zero-copy pull path: transport bytes-copied per
    payload byte, mux vs legacy."""
    import socketserver
    import threading

    from paddle_tpu.distributed.fleet.runtime import rpc

    class _Srv(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self):
            state = rpc.RpcServerState(
                read_ops=frozenset({"ping", "pull", "gen"}))

            def dispatch(req):
                op = req["op"]
                if op == "ping":
                    return "pong"
                if op == "pull":
                    n, d = int(req["n"]), int(req["d"])
                    return {"rows": np.zeros((n, d), np.float32)}

                def g():
                    for i in range(int(req["n"])):
                        time.sleep(float(req.get("gap", 0.02)))
                        yield {"i": i}
                    return {"done": True}
                return g()

            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    rpc.serve_connection(self.request, dispatch, state)

            super().__init__(("127.0.0.1", 0), H)
            self.endpoint = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever,
                             daemon=True).start()

    def _copied(path):
        for vals, child in rpc._MUX_BYTES_COPIED._series():
            if vals == (path,):
                return child.value
        return 0.0

    srv = _Srv()
    modes = {}
    for mode, mux in (("mux", True), ("legacy", False)):
        cli = rpc.RpcClient(srv.endpoint, mux=mux, pool_size=2,
                            timeout=30.0, deadline=60.0)
        sweep = {}
        for conc in concurrencies:
            stop = threading.Event()

            def pump():
                # a continuous long stream occupying the shared client
                while not stop.is_set():
                    gen = cli.call_stream(
                        {"op": "gen", "n": 10, "gap": 0.03},
                        timeout=30, stream_timeout=30)
                    try:
                        for _ in gen:
                            if stop.is_set():
                                break
                    finally:
                        gen.close()

            threads = [threading.Thread(target=pump, daemon=True)
                       for _ in range(conc)]
            for th in threads:
                th.start()
            time.sleep(0.2)      # streams in flight before probing
            lats = []
            for _ in range(probes):
                t0 = time.perf_counter()
                gen = cli.call_stream({"op": "gen", "n": 1, "gap": 0.0},
                                      timeout=30, stream_timeout=30)
                next(gen)        # FIRST frame = wire TTFT
                lats.append(time.perf_counter() - t0)
                for _ in gen:    # drain the final reply
                    pass
            stop.set()
            for th in threads:
                th.join(timeout=30)
            lats.sort()
            sweep[conc] = {
                "ttft_p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
                "ttft_p99_ms": round(
                    lats[min(len(lats) - 1,
                             int(0.99 * len(lats)))] * 1e3, 2)}
        # zero-copy pull path: bytes memcpy'd per payload byte
        n, d, reps = 512, 64, 8
        path = "mux" if mux else "legacy"
        c0 = _copied(path)
        for _ in range(reps):
            cli.call({"op": "pull", "n": n, "d": d}, timeout=30)
        copied_per_byte = (_copied(path) - c0) / (reps * n * d * 4)
        cli.close()
        modes[mode] = {"ttft": sweep,
                       "pull_bytes_copied_per_payload_byte":
                       round(copied_per_byte, 4)}
    srv.shutdown()
    srv.server_close()
    top = max(concurrencies)
    mux_p99 = modes["mux"]["ttft"][top]["ttft_p99_ms"]
    legacy_p99 = modes["legacy"]["ttft"][top]["ttft_p99_ms"]
    return {"metric": "transport_wire_ttft_p99_ms",
            "value": mux_p99, "unit": "ms",
            "concurrency": top, "probes": probes,
            "p99_speedup_vs_legacy": round(legacy_p99 / mux_p99, 2)
            if mux_p99 else None,
            "modes": modes}


def bench_slo(duration=6.0, rate=30.0, seed=7):
    """Production traffic replay (docs/SERVING.md harness): a seeded
    open-loop diurnal mix of prompt/output lengths, tenants and
    priority tiers drives the serving engine; reports SLO attainment
    (met/offered), goodput (tokens from requests that met their
    deadline) and p99 TTFT / inter-token latency at that offered
    load."""
    from paddle_tpu.serving import LoadGenerator, slo_report

    eng = _slo_engine()
    gen = LoadGenerator(_slo_traffic(duration, rate, seed),
                        name="bench_slo")
    with eng:
        res = gen.run_engine(eng)
        finished = res.wait(300)
    rep = slo_report(res)
    st = eng.stats()
    return {"metric": "serving_slo_attainment",
            "value": rep["attainment"], "unit": "met/offered",
            "offered": rep["offered"],
            "offered_rate_rps": rate, "duration_s": duration,
            "goodput_tokens_per_sec": rep["goodput_tokens_per_sec"],
            "ttft_ms_p50": rep["ttft_ms_p50"],
            "ttft_ms_p99": rep["ttft_ms_p99"],
            "itl_ms_p99": rep["itl_ms_p99"],
            "by_status": rep["by_status"],
            "shed": st["shed"], "preemptions": st["preemptions"],
            "expired_in_queue": st["expired_in_queue"],
            "all_finished": bool(finished)}


def bench_prefix(num_requests=24, pool_prompts=2, prefix_len=64,
                 suffix_len=8, max_new=8, num_slots=8, seed=0):
    """BENCH_CONFIG=prefix (docs/SERVING.md shared-prefix section):
    the radix prefix cache A/B'd on the workload it exists for — every
    request is one of `pool_prompts` long system prompts plus a unique
    user suffix. The SAME request mix runs cache-off then cache-on
    (both warmed so XLA compiles never land in a timed window) and the
    record reports goodput tokens/s, p99 TTFT, the prefill-compute
    reduction (prefill cost is token-proportional at one model config,
    so saved prefill tokens ARE saved prefill FLOPs), and the measured
    effective-KV-capacity multiplier: logical KV pages the live batch
    addresses per physical page allocated (1.0 unshared; the
    acceptance bar is >= 2x on this mix)."""
    import threading

    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import Engine, GPTDecodeModel

    cfg = GPTConfig(hidden_size=256, num_layers=4, num_heads=4,
                    max_position_embeddings=256, vocab_size=4096)
    model = GPTDecodeModel(cfg, seed=seed)
    rng = np.random.RandomState(seed)
    pool = [rng.randint(0, cfg.vocab_size,
                        (prefix_len,)).astype(np.int32)
            for _ in range(pool_prompts)]
    prompts = []
    for i in range(num_requests):
        sfx = rng.randint(0, cfg.vocab_size,
                          (suffix_len,)).astype(np.int32)
        prompts.append(np.concatenate([pool[i % pool_prompts], sfx]))
    total_prompt_tokens = sum(int(p.size) for p in prompts)

    def run(cache_pages):
        eng = Engine(model, num_slots=num_slots, num_pages=128,
                     page_size=8, max_seq_len=96,
                     prefix_cache_pages=cache_pages)
        peak = {"mult": 1.0, "used": 0}
        stop = threading.Event()

        def sampler():
            # effective KV capacity, measured live: logical pages the
            # active batch addresses vs DISTINCT physical pages backing
            # them (shared pages counted once). Read-only racy peek at
            # the slot array — a torn read mid-admission just skips one
            # sample.
            while not stop.is_set():
                try:
                    live = [r for r in eng.scheduler.slots
                            if r is not None]
                    logical = sum(len(r.table.pages) for r in live)
                    phys = len({p for r in live for p in r.table.pages})
                    if phys and len(live) >= num_slots // 2:
                        peak["mult"] = max(peak["mult"],
                                           logical / phys)
                    peak["used"] = max(peak["used"],
                                       eng.pool.stats()["used_pages"])
                except Exception:
                    pass
                time.sleep(0.002)
        with eng:
            # warmup compiles every bucket this mix touches and leaves
            # the cache hot, so the timed window measures steady-state
            # serving. The suffixes must DIFFER: a repeat of the same
            # prompt is a full-prompt match (bootstrap, no prefill at
            # all), and the prefill_tail bucket would then pay its XLA
            # compile inside the timed window
            for pfx in pool:
                for _ in range(2):
                    w = np.concatenate([pfx, rng.randint(
                        0, cfg.vocab_size,
                        (suffix_len,)).astype(np.int32)])
                    eng.generate(w, 2)
            pre = eng.stats()["prefix_cache"] or {}
            th = threading.Thread(target=sampler, daemon=True)
            th.start()
            t0 = time.perf_counter()
            reqs = [eng.submit(p, max_new) for p in prompts]
            eng.run_until_idle()
            dt = time.perf_counter() - t0
            stop.set()
            th.join(timeout=5)
            post = eng.stats()["prefix_cache"] or {}
            st = eng.stats()
        ntok = sum(len(r.generated) for r in reqs)
        ttfts = sorted(r.ttft() for r in reqs if r.ttft() is not None)
        saved = post.get("tokens_saved", 0) - pre.get("tokens_saved", 0)
        return {
            "goodput_tokens_per_sec": round(ntok / dt, 1),
            "ttft_ms_p50": round(ttfts[len(ttfts) // 2] * 1e3, 2),
            "ttft_ms_p99": round(ttfts[min(len(ttfts) - 1,
                                           int(0.99 * len(ttfts)))]
                                 * 1e3, 2),
            "prefill_tokens_saved": int(saved),
            "prefill_flops_reduction": round(
                saved / total_prompt_tokens, 4),
            "kv_capacity_multiplier": round(peak["mult"], 2),
            "peak_used_pages": peak["used"],
            "compiles": st["compiles"],
            "cache": post or None,
        }

    off = run(0)
    on = run(64)
    off_p99 = off["ttft_ms_p99"]
    return {"metric": "prefix_cache_kv_capacity_multiplier",
            "value": on["kv_capacity_multiplier"], "unit": "x logical/physical",
            "requests": num_requests, "pool_prompts": pool_prompts,
            "prefix_len": prefix_len, "suffix_len": suffix_len,
            "max_new": max_new,
            "goodput_speedup": round(
                on["goodput_tokens_per_sec"]
                / max(1e-9, off["goodput_tokens_per_sec"]), 2),
            "ttft_p99_speedup": round(
                off_p99 / max(1e-9, on["ttft_ms_p99"]), 2),
            "prefill_flops_reduction": on["prefill_flops_reduction"],
            "cache_on": on, "cache_off": off}


def bench_chaos(duration=8.0, rate=25.0, seed=7, stall_s=0.8,
                wd_deadline=0.5):
    """Chaos drill as a bench (docs/DEBUGGING.md recipe): the SAME
    seeded traffic replayed twice — fault-free baseline, then with the
    serving_decode stall knob wedging the step thread mid-run. Reports
    watchdog detection seconds, recovery seconds (fault armed ->
    progress again), and the post-recovery SLO attainment delta vs the
    baseline's identical traffic slice."""
    import threading

    from paddle_tpu.distributed.fleet.runtime import (
        fault_injection as fi)
    from paddle_tpu.observability.watchdog import WATCHDOG
    from paddle_tpu.serving import LoadGenerator, slo_report

    mk_gen = lambda name: LoadGenerator(
        _slo_traffic(duration, rate, seed), name=name)
    eng_a = _slo_engine()
    with eng_a:
        res_a = mk_gen("chaos_base").run_engine(eng_a)
        res_a.wait(300)
    base = slo_report(res_a)

    # the engine's watchdog token captures its deadline at registration
    prev = os.environ.get("PADDLE_TPU_WATCHDOG_DEADLINE")
    os.environ["PADDLE_TPU_WATCHDOG_DEADLINE"] = str(wd_deadline)
    try:
        eng_b = _slo_engine()
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_WATCHDOG_DEADLINE", None)
        else:
            os.environ["PADDLE_TPU_WATCHDOG_DEADLINE"] = prev
    token = f"serving.engine.{eng_b.engine_id}"
    box = []
    detect_s = recovery_s = None
    with eng_b:
        runner = threading.Thread(
            target=lambda: box.append(
                mk_gen("chaos_fault").run_engine(eng_b)), daemon=True)
        runner.start()
        time.sleep(min(1.0, duration / 4))          # traffic flowing
        t_fault = time.monotonic()
        fi.reset_injector(fi.FaultInjector(
            stall=stall_s, stall_point="serving_decode"))
        while detect_s is None \
                and time.monotonic() - t_fault < 30:
            # level-triggered stalled(), not check_once()'s fire
            # event: an auto-started watchdog poll thread
            # (PADDLE_TPU_WATCHDOG=1) would consume the edge
            WATCHDOG.check_once()
            if token in WATCHDOG.stalled():
                detect_s = time.monotonic() - t_fault
            time.sleep(0.05)
        fi.reset_injector(fi.FaultInjector())
        t_cleared = time.monotonic()
        while recovery_s is None \
                and time.monotonic() - t_cleared < 30:
            WATCHDOG.check_once()
            if token not in WATCHDOG.stalled():
                recovery_s = time.monotonic() - t_fault
            time.sleep(0.05)
        runner.join(timeout=300)
        res_b = box[0] if box else None
        if res_b is not None:
            res_b.wait(300)
    faulted = slo_report(res_b) if res_b is not None else None
    # post-recovery window: identical arrivals in both runs
    post = post_base = None
    if res_b is not None and recovery_s is not None:
        rec_off = (t_cleared + stall_s) - res_b.started_at
        if rec_off < duration - 0.5:
            post = slo_report(res_b, window=(rec_off, float("inf")),
                              gen="chaos_post")
            post_base = slo_report(res_a,
                                   window=(rec_off, float("inf")),
                                   gen="chaos_post_base")
    delta = None
    if post is not None and post_base is not None \
            and post_base["attainment"] is not None:
        delta = round(post_base["attainment"] - post["attainment"], 4)
    return {"metric": "serving_chaos_slo_delta", "value": delta,
            "unit": "attainment_drop_post_recovery",
            "fault": f"stall@serving_decode {stall_s}s",
            "detect_s": None if detect_s is None
            else round(detect_s, 3),
            "recovery_s": None if recovery_s is None
            else round(recovery_s, 3),
            "baseline_attainment": base["attainment"],
            "faulted_attainment": None if faulted is None
            else faulted["attainment"],
            "post_recovery_attainment": None if post is None
            else post["attainment"],
            "post_recovery_baseline": None if post_base is None
            else post_base["attainment"],
            "baseline_goodput_tokens_per_sec":
                base["goodput_tokens_per_sec"],
            "faulted_goodput_tokens_per_sec": None if faulted is None
            else faulted["goodput_tokens_per_sec"],
            "offered_rate_rps": rate, "duration_s": duration}


def bench_router(duration=8.0, rate=25.0, seed=7, kill_at=2.5):
    """BENCH_CONFIG=router (docs/SERVING.md replicated serving): the
    SAME seeded traffic replayed twice over the WIRE through the
    fault-tolerant router fronting two replicas — fault-free baseline,
    then with one replica killed mid-run (listener + live connections
    severed, decode loop halted). Reports failover detect seconds
    (kill -> replica out of rotation), recovery seconds (kill ->
    respawned-from-checkpoint replica healthy again), post-recovery
    attainment delta vs the baseline's identical traffic slice, and
    wire TTFT (streaming generate), mirroring BENCH_CONFIG=chaos."""
    import tempfile
    import threading

    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.serving import (GPTDecodeModel, InProcessReplica,
                                    LoadGenerator, Router,
                                    ServingClient, slo_report)

    root = os.path.join(tempfile.mkdtemp(prefix="bench_router_"), "gpt")
    cfg = GPTConfig(hidden_size=256, num_layers=4, num_heads=4,
                    max_position_embeddings=256, vocab_size=4096)
    GPTDecodeModel(cfg, seed=0).save_checkpoint(root)
    engine_kw = dict(num_slots=8, num_pages=128, page_size=8,
                     max_seq_len=96)

    def fleet():
        reps = []
        for i in range(2):
            r = InProcessReplica(root, name=f"rep{i}",
                                 engine_kw=engine_kw)
            r.start()
            for plen in (4, 8, 16, 32):   # compile outside the window
                r.engine.submit(np.full((plen,), 1, np.int32), 2)
            r.engine.run_until_idle()
            reps.append(r)
        router = Router("127.0.0.1:0",
                        replicas=[r.spec() for r in reps],
                        ping_interval=0.2, ping_timeout=1.0,
                        suspect_after=1, dead_after=2, token_stall=5.0,
                        respawn_cooldown=0.5)
        return router, reps

    mk_gen = lambda name: LoadGenerator(
        _slo_traffic(duration, rate, seed), name=name)

    router_a, reps_a = fleet()
    with router_a:
        cli = ServingClient(router_a.endpoint)
        res_a = mk_gen("router_base").run_client(cli, timeout=120)
        res_a.wait(300)
        cli.close()
    for r in reps_a:
        r.stop()
    base = slo_report(res_a)

    router_b, reps_b = fleet()
    detect_s = recovery_s = None
    t_kill = None
    with router_b:
        cli = ServingClient(router_b.endpoint)
        box = []
        runner = threading.Thread(
            target=lambda: box.append(
                mk_gen("router_fault").run_client(cli, timeout=120)),
            daemon=True)
        runner.start()
        time.sleep(kill_at)
        t_kill = time.monotonic()
        reps_b[1].kill()
        while time.monotonic() - t_kill < 60 \
                and (detect_s is None or recovery_s is None):
            state = router_b.stats()["replicas"]["rep1"]["state"]
            if detect_s is None and state != "healthy":
                detect_s = time.monotonic() - t_kill
            if detect_s is not None and state == "healthy":
                recovery_s = time.monotonic() - t_kill
            time.sleep(0.05)
        runner.join(300)
        res_b = box[0] if box else None
        if res_b is not None:
            res_b.wait(300)
        cli.close()
    for r in reps_b:
        r.stop()
    faulted = slo_report(res_b) if res_b is not None else None
    fo = REGISTRY.get("paddle_tpu_router_failovers_total")
    failovers = sum(s.value for lv, s in fo._series()
                    if lv[0] == router_b.router_id)
    post = post_base = None
    if res_b is not None and recovery_s is not None:
        rec_off = (t_kill + recovery_s) - res_b.started_at
        if rec_off < duration - 0.5:
            post = slo_report(res_b, window=(rec_off, float("inf")),
                              gen="router_post")
            post_base = slo_report(res_a,
                                   window=(rec_off, float("inf")),
                                   gen="router_post_base")
    delta = None
    if post is not None and post_base is not None \
            and post_base["attainment"] is not None:
        delta = round(post_base["attainment"] - post["attainment"], 4)
    return {"metric": "serving_router_slo_delta", "value": delta,
            "unit": "attainment_drop_post_recovery",
            "fault": f"replica kill @ {kill_at}s of {duration}s",
            "detect_s": None if detect_s is None
            else round(detect_s, 3),
            "recovery_s": None if recovery_s is None
            else round(recovery_s, 3),
            "failovers": int(failovers),
            "baseline_attainment": base["attainment"],
            "faulted_attainment": None if faulted is None
            else faulted["attainment"],
            "post_recovery_attainment": None if post is None
            else post["attainment"],
            "post_recovery_baseline": None if post_base is None
            else post_base["attainment"],
            "wire_ttft_ms_p50": base["ttft_ms_p50"],
            "wire_ttft_ms_p99": base["ttft_ms_p99"],
            "wire_itl_ms_p99": base["itl_ms_p99"],
            "offered_rate_rps": rate, "duration_s": duration}


def bench_online(staleness_rounds=5, cadence_steps=3, stream_tokens=64,
                 dedup_rows=512, dedup_dim=256, seed=0):
    """BENCH_CONFIG=online (docs/ONLINE_LEARNING.md): the continuous
    publish pipeline end to end. Three numbers: (1) publish staleness
    — PS training pushes into a publish-wired PSServer; time from the
    cadence-triggering commit to the new version answering on the
    pub_latest wire (manifest + registry both durable, i.e. servable);
    (2) swap pause — a streamed wire generate spans a staggered
    2-replica rollout; max inter-token gap inside the flip window vs
    the same stream's gap outside it (the adopt happens under the
    engine step lock, so the pause should be ~one weight load, not a
    drain); (3) cross-version chunk dedup — a one-row-mutated
    embedding republished through the content-addressed store."""
    import tempfile
    import threading

    from paddle_tpu.distributed.fleet.runtime.parameter_server_runtime \
        import PSClient, PSServer
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.publish import Publisher, RegistryClient
    from paddle_tpu.serving import (GPTDecodeModel, InProcessReplica,
                                    Router, ServingClient)

    base = tempfile.mkdtemp(prefix="bench_online_")

    # -- (1) train-push -> servable staleness over the PS wire --------
    ps_pub = os.path.join(base, "ps_pub")
    srv = PSServer("127.0.0.1:0", publish_dir=ps_pub,
                   publish_every_steps=cadence_steps)
    srv.serve_in_thread()
    cl = PSClient([srv.endpoint])
    watcher = RegistryClient(srv.endpoint)
    rng = np.random.RandomState(seed)
    staleness = []
    try:
        for round_i in range(staleness_rounds):
            for j in range(cadence_steps):
                ids = np.arange(j * 8, j * 8 + 8)
                t0 = time.perf_counter()
                cl.push("emb", 64, ids, rng.randn(8, 64))
            want = round_i + 1
            while watcher.latest()["latest"] < want:
                time.sleep(0.002)
            staleness.append(time.perf_counter() - t0)
    finally:
        watcher.close()
        cl.close()
        srv.shutdown()
        srv.server_close()
    staleness.sort()
    stale_p50 = staleness[len(staleness) // 2]

    # -- (2) swap pause on the wire -----------------------------------
    ckpt = os.path.join(base, "gpt")
    pub = os.path.join(base, "pub")
    cfg = GPTConfig(hidden_size=256, num_layers=4, num_heads=4,
                    max_position_embeddings=256, vocab_size=4096)
    GPTDecodeModel(cfg, seed=seed).save_checkpoint(ckpt)
    engine_kw = dict(num_slots=8, num_pages=128, page_size=8,
                     max_seq_len=96)
    reps = []
    for i in range(2):
        r = InProcessReplica(ckpt, name=f"rep{i}", engine_kw=engine_kw,
                             publish_root=pub)
        r.start()
        r.engine.submit(np.full((4,), 1, np.int32), 2)
        r.engine.run_until_idle()   # compile outside the window
        reps.append(r)
    router = Router("127.0.0.1:0", replicas=[r.spec() for r in reps],
                    ping_interval=0.2, ping_timeout=1.0,
                    suspect_after=1, dead_after=2, token_stall=5.0,
                    respawn_cooldown=0.5, publish_root=pub)
    frames = []          # (arrival_monotonic, index)
    flip = {}
    with router:
        cli = ServingClient(router.endpoint)
        try:
            def publish_and_roll():
                # flip once the stream is warmed up (a few frames in)
                while len(frames) < 4:
                    time.sleep(0.005)
                Publisher(pub).publish_model(
                    GPTDecodeModel(cfg, seed=seed + 1), step=100)
                flip["t0"] = time.monotonic()
                flip["res"] = router.rollout_version()
                flip["t1"] = time.monotonic()

            flipper = threading.Thread(target=publish_and_roll,
                                       daemon=True)
            flipper.start()
            cli.generate(np.array([9, 8, 7], np.int32),
                         max_new_tokens=stream_tokens, stream=True,
                         on_token=lambda toks, idx: frames.append(
                             (time.monotonic(), idx)))
            flipper.join(120)
        finally:
            cli.close()
    for r in reps:
        r.stop()
    gaps_in, gaps_out = [], []
    for (t_prev, _i0), (t_cur, _i1) in zip(frames, frames[1:]):
        gap = t_cur - t_prev
        if "t0" in flip and flip["t0"] <= t_cur <= flip["t1"] + 0.05:
            gaps_in.append(gap)
        else:
            gaps_out.append(gap)
    pause_ms = max(gaps_in) * 1e3 if gaps_in else 0.0
    steady_ms = (sorted(gaps_out)[len(gaps_out) // 2] * 1e3
                 if gaps_out else 0.0)

    # -- (3) cross-version chunk dedup --------------------------------
    # chunk grid smaller than the table so a one-row delta shares all
    # untouched chunks with the previous version (the production-scale
    # shape; at the default chunk size this toy table is ONE chunk)
    from paddle_tpu.checkpoint import CheckpointStore
    dedup_root = os.path.join(base, "dedup")
    dpub = Publisher(dedup_root,
                     store=CheckpointStore(dedup_root,
                                           chunk_bytes=16384))
    table = np.random.RandomState(seed + 2).randn(
        dedup_rows, dedup_dim).astype(np.float32)
    dpub.publish_arrays({"r:emb": table}, step=1, kind="ps-table")
    table[dedup_rows // 2, :] += 1.0   # one-row online update
    t0 = time.perf_counter()
    rec2 = dpub.publish_arrays({"r:emb": table}, step=2,
                               kind="ps-table")
    publish_s = time.perf_counter() - t0
    return {"metric": "online_publish_staleness_s",
            "value": round(stale_p50, 4), "unit": "s_push_to_servable",
            "staleness_p50_s": round(stale_p50, 4),
            "staleness_max_s": round(staleness[-1], 4),
            "cadence_steps": cadence_steps,
            "swap_pause_ms": round(pause_ms, 2),
            "steady_itl_ms": round(steady_ms, 2),
            "rollout_wall_s": round(flip["t1"] - flip["t0"], 3)
            if "t1" in flip else None,
            "rollout_adopted": (flip.get("res") or {}).get("adopted"),
            "stream_frames": len(frames),
            "dedup_ratio": round(float(
                rec2["extra"]["dedup"]), 4),
            "dedup_republish_s": round(publish_s, 4),
            "dedup_array_mb": round(table.nbytes / 2**20, 2)}


def _bench_serving_toggle_overhead(set_enabled, metric_name, steps=200,
                                   hidden=256, layers=4, heads=4,
                                   slots=4, seed=0):
    """Shared A/B/A harness: decode step time with some telemetry
    subsystem enabled vs disabled (``set_enabled(bool)``) on the SAME
    engine (same compiled programs, same slot occupancy). A/B/A
    ordering (on, off, on) so cache warmup or clock drift cannot
    masquerade as telemetry cost."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import Engine, GPTDecodeModel

    cfg = GPTConfig(hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=512,
                    vocab_size=8192)
    model = GPTDecodeModel(cfg, seed=seed)
    eng = Engine(model, num_slots=slots, num_pages=128, page_size=16,
                 max_seq_len=448)
    rng = np.random.RandomState(seed)

    def timed(n_steps):
        # keep every slot busy for the whole window (big token budget),
        # then time pure decode steps
        reqs = [eng.submit(rng.randint(0, cfg.vocab_size, (16,)),
                           max_new_tokens=420) for _ in range(slots)]
        for _ in range(5):
            eng.step()  # prefills + first decodes
        t0 = time.perf_counter()
        for _ in range(n_steps):
            eng.step()
        dt = (time.perf_counter() - t0) / n_steps
        for r in reqs:
            eng.cancel(r)
        return dt

    timed(20)  # compile both programs outside the measurement
    on1 = timed(steps)
    set_enabled(False)
    try:
        off = timed(steps)
    finally:
        set_enabled(True)
    on2 = timed(steps)
    on = min(on1, on2)
    overhead = (on - off) / off * 100 if off > 0 else 0.0
    return {"metric": metric_name,
            "value": round(overhead, 2), "unit": "%",
            "enabled_step_ms": round(on * 1e3, 4),
            "disabled_step_ms": round(off * 1e3, 4),
            "enabled_runs_ms": [round(on1 * 1e3, 4),
                                round(on2 * 1e3, 4)],
            "steps": steps, "slots": slots,
            "model": f"gpt-h{hidden}-l{layers}"}


def bench_metrics_overhead(steps=200, hidden=256, layers=4, heads=4,
                           slots=4, seed=0):
    """Telemetry cost guardrail: the whole observability substrate
    (registry + tracer + flight recorder) enabled vs disabled. The
    acceptance bar is <2% overhead enabled — the counters/spans/events
    on the Engine.step hot path are host-side microseconds against a
    millisecond jitted decode."""
    from paddle_tpu import observability as obs
    return _bench_serving_toggle_overhead(
        obs.set_enabled, "serving_metrics_overhead_pct", steps=steps,
        hidden=hidden, layers=layers, heads=heads, slots=slots,
        seed=seed)


def bench_flight_overhead(steps=200, hidden=256, layers=4, heads=4,
                          slots=4, seed=0):
    """Flight-recorder cost guardrail (ISSUE 5 acceptance): ONLY the
    flight rings toggled — registry and tracer stay on both ways, so
    the delta isolates the recorder's per-event cost (ring append
    under one lock + two counter incs) on the decode hot path. Same
    <2% bar as metrics_overhead."""
    from paddle_tpu.observability import flight
    return _bench_serving_toggle_overhead(
        flight.RECORDER.set_enabled, "serving_flight_overhead_pct",
        steps=steps, hidden=hidden, layers=layers, heads=heads,
        slots=slots, seed=seed)


def bench_telemetry_overhead(steps=200, hidden=256, layers=4, heads=4,
                             slots=4, seed=0):
    """Fleet-telemetry cost guardrail (ISSUE 13 acceptance): a LIVE
    TelemetryAgent streaming spans/flight events to an in-process
    collector, toggled A/B/A on the same engine. The agent's sinks are
    bounded-queue appends and all socket IO rides the agent's own
    thread, so the decode hot path should see the same <2% bar as the
    other observability toggles."""
    from paddle_tpu.observability import agent as tel_agent
    from paddle_tpu.observability.collector import CollectorServer

    srv = CollectorServer("127.0.0.1:0").start()

    def set_enabled(on):
        if on:
            tel_agent.arm(srv.endpoint)
        else:
            tel_agent.disarm()

    set_enabled(True)
    try:
        return _bench_serving_toggle_overhead(
            set_enabled, "serving_telemetry_overhead_pct", steps=steps,
            hidden=hidden, layers=layers, heads=heads, slots=slots,
            seed=seed)
    finally:
        tel_agent.disarm()
        srv.stop()


def bench_perfwatch_overhead(steps=200, hidden=256, layers=4, heads=4,
                             slots=4, seed=0):
    """Perf-plane cost guardrail (ISSUE 14 acceptance): the step
    sampler toggled A/B/A at its DEFAULT cadence vs fully off on the
    same engine. Between samples the decode hot path only pays one
    sampler tick (an int increment + modulo); a sampled step adds a
    block_until_ready fence the following np.asarray would have paid
    anyway. Same <2% bar as the other observability toggles."""
    from paddle_tpu.observability import perf

    default_every = perf.sampling_every() or 50

    def set_enabled(on):
        perf.set_every(default_every if on else 0)

    set_enabled(True)
    try:
        return _bench_serving_toggle_overhead(
            set_enabled, "serving_perfwatch_overhead_pct", steps=steps,
            hidden=hidden, layers=layers, heads=heads, slots=slots,
            seed=seed)
    finally:
        perf.set_every(default_every)


def bench_checkpoint(state_mb=64, train_steps=150, save_every=50,
                     hidden=1024, seed=0):
    """Checkpoint-store economics (ISSUE 4 acceptance): save/restore
    MB/s, the dedup ratio of a 1%-mutated re-save (content-addressed
    chunks re-referenced, not rewritten), and the train-step overhead
    of saving every `save_every` steps — async (host-copy + background
    writer) vs sync (blocking chunk IO), A/B/A wall-clock against a
    no-save baseline. Bar: async <5% at the benched cadence. Note the
    cadence is already ~100x compressed vs real jobs (one save per
    ~0.5s of stepping vs one per minutes), and on a CPU-only host the
    background writer competes with XLA for the same cores — a TPU
    host pays only the host-copy slice, so the CPU number is the
    worst case. Per-save interference is recorded so any cadence can
    be extrapolated."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.checkpoint import CheckpointStore

    rs = np.random.RandomState(seed)
    per = state_mb * (1 << 20) // 4 // 8
    state = {f"w{i}": rs.randn(per).astype(np.float32)
             for i in range(8)}
    nbytes = sum(a.nbytes for a in state.values())
    root = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        st = CheckpointStore(root)
        t0 = time.perf_counter()
        st.save(state)
        save_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out, _ = st.restore()
        restore_s = time.perf_counter() - t0
        del out

        # 1%-mutated re-save: dedup ratio + bytes actually written.
        # The mutation is 1% of TOTAL state bytes, contiguous (the
        # "touched embedding rows" pattern) — chunk-granular dedup
        # keeps every untouched chunk
        mutated = dict(state)
        b = state["w0"].copy()
        n_mut = max(1, (8 * len(b)) // 100)
        b[:n_mut] += 1.0
        mutated["w0"] = b
        w0, h0 = st.chunks.chunks_written, st.chunks.dedup_hits
        bytes0 = st.chunks.bytes_written
        t0 = time.perf_counter()
        st.save(mutated)
        incr_s = time.perf_counter() - t0
        new_chunks = st.chunks.chunks_written - w0
        hits = st.chunks.dedup_hits - h0
        dedup_ratio = hits / max(new_chunks + hits, 1)
        incr_bytes = st.chunks.bytes_written - bytes0

        # async-vs-sync step overhead on a real jitted train step
        p = jnp.asarray(rs.randn(hidden, hidden).astype(np.float32))
        x = jnp.asarray(rs.randn(64, hidden).astype(np.float32))

        @jax.jit
        def step(p, x):
            def loss(p):
                h = jnp.tanh(x @ p)
                h = jnp.tanh(h @ p)
                return jnp.sum(h * h)
            g = jax.grad(loss)(p)
            return p - 1e-4 * g

        n_saves = (train_steps + save_every - 1) // save_every

        def run(mode, store):
            nonlocal p
            _sync(step(p, x))  # warm
            t0 = time.perf_counter()
            for i in range(train_steps):
                p = step(p, x)
                if store is not None and i % save_every == 0:
                    if mode == "async":
                        store.save_async({"p": p})
                    else:
                        store.save({"p": p})
            _sync(p)
            if store is not None:
                store.wait()
            return (time.perf_counter() - t0) / train_steps

        base1 = run("none", None)
        async_root = tempfile.mkdtemp(prefix="ckpt_bench_a_")
        sync_root = tempfile.mkdtemp(prefix="ckpt_bench_s_")
        try:
            t_async = run("async", CheckpointStore(async_root))
            t_sync = run("sync", CheckpointStore(sync_root))
        finally:
            shutil.rmtree(async_root, ignore_errors=True)
            shutil.rmtree(sync_root, ignore_errors=True)
        base2 = run("none", None)
        base = min(base1, base2)
        async_pct = (t_async - base) / base * 100 if base > 0 else 0.0
        sync_pct = (t_sync - base) / base * 100 if base > 0 else 0.0
        async_ms_per_save = (t_async - base) * train_steps * 1e3 \
            / n_saves
        sync_ms_per_save = (t_sync - base) * train_steps * 1e3 \
            / n_saves
        return {"metric": "ckpt_save_MBps",
                "value": round(nbytes / (1 << 20) / save_s, 1),
                "unit": "MB/s",
                "restore_MBps": round(nbytes / (1 << 20) / restore_s,
                                      1),
                "state_mb": state_mb,
                "incremental_save_s": round(incr_s, 4),
                "incremental_bytes_written": int(incr_bytes),
                "dedup_ratio_1pct_mutation": round(dedup_ratio, 4),
                "async_save_overhead_pct": round(async_pct, 2),
                "sync_save_overhead_pct": round(sync_pct, 2),
                "async_overhead_bar_pct": 5.0,
                "async_interference_ms_per_save":
                    round(async_ms_per_save, 2),
                "sync_blocked_ms_per_save":
                    round(sync_ms_per_save, 2),
                "baseline_step_ms": round(base * 1e3, 4),
                "async_step_ms": round(t_async * 1e3, 4),
                "sync_step_ms": round(t_sync * 1e3, 4),
                "save_every": save_every,
                "train_steps": train_steps}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_elastic(train_steps=120, save_every=30, hidden=512, seed=0):
    """BENCH_CONFIG=elastic (docs/ELASTIC.md): the economics of the
    elastic-training substrate. Three numbers:

    - cluster-checkpoint cadence overhead, async vs sync, A/B/A
      wall-clock against a no-save baseline on a jitted train step
      (bar: async <5% at the benched cadence, same as checkpoint);
    - detect→resume wall time of a SIGKILL-mid-step gang restart
      through the real launcher (kill at step 7, backoff 0.05s),
      measured as the largest inter-record gap in the drill fixture's
      per-step jsonl;
    - loss-continuation delta of the resumed run vs a fault-free one
      (bit-for-bit at the same world ⇒ 0.0)."""
    import json as _json
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.cluster_ckpt import ClusterCheckpoint

    rs = np.random.RandomState(seed)
    p = jnp.asarray(rs.randn(hidden, hidden).astype(np.float32))
    x = jnp.asarray(rs.randn(64, hidden).astype(np.float32))

    @jax.jit
    def step(p, x):
        def loss(p):
            h = jnp.tanh(x @ p)
            h = jnp.tanh(h @ p)
            return jnp.sum(h * h)
        g = jax.grad(loss)(p)
        return p - 1e-4 * g

    def run(ck):
        nonlocal p
        _sync(step(p, x))  # warm
        t0 = time.perf_counter()
        for i in range(train_steps):
            p = step(p, x)
            if ck is not None:
                ck.maybe_save(i, replicated={"p": p})
        _sync(p)
        if ck is not None:
            ck.wait()
        return (time.perf_counter() - t0) / train_steps

    def cadenced(async_save):
        root = tempfile.mkdtemp(prefix="elastic_bench_")
        try:
            return run(ClusterCheckpoint(
                root, rank=0, world=1, every_steps=save_every,
                async_save=async_save))
        finally:
            shutil.rmtree(root, ignore_errors=True)

    base1 = run(None)
    t_async = cadenced(True)
    t_sync = cadenced(False)
    base2 = run(None)
    base = min(base1, base2)
    async_pct = (t_async - base) / base * 100 if base > 0 else 0.0
    sync_pct = (t_sync - base) / base * 100 if base > 0 else 0.0

    # gang-restart drill through the real launcher (fixture arms a
    # deterministic kill at step 7; resumed life recomputes from the
    # committed step)
    repo = os.path.dirname(os.path.abspath(__file__))
    fixture = os.path.join(repo, "tests", "fixtures",
                           "elastic_trainer.py")

    def free_port():
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def drill(extra_env, launcher_args):
        work = tempfile.mkdtemp(prefix="elastic_drill_")
        out, ckpt = os.path.join(work, "out"), os.path.join(work, "c")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   ELASTIC_DRILL_OUT=out,
                   ELASTIC_DRILL_STEPS="12",
                   ELASTIC_DRILL_SAVE_EVERY="2",
                   ELASTIC_DRILL_STEP_SLEEP="0.02", **extra_env)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        env.pop("XLA_FLAGS", None)
        res = subprocess.run(
            [_sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=2", f"--started_port={free_port()}",
             "--log_dir", os.path.join(work, "logs"),
             f"--cluster_ckpt_dir={ckpt}"] + launcher_args + [fixture],
            env=env, capture_output=True, text=True, timeout=300)
        recs = []
        for r in range(2):
            path = os.path.join(out, f"loss_rank{r}.jsonl")
            if os.path.exists(path):
                with open(path) as f:
                    recs += [_json.loads(ln) for ln in f]
        curve = {}
        for rec in sorted(recs, key=lambda r: r["t"]):
            if rec["rank"] == 0:
                curve[rec["step"]] = rec["loss"]
        shutil.rmtree(work, ignore_errors=True)
        return res.returncode, recs, curve

    rc0, _, want = drill({}, [])
    rc1, recs, got = drill(
        {"ELASTIC_DRILL_KILL_RANK": "1", "ELASTIC_DRILL_KILL_AT": "7"},
        ["--max_restarts=2", "--restart_backoff=0.05"])
    ts = sorted(r["t"] for r in recs)
    detect_resume_s = max(b - a for a, b in zip(ts, ts[1:])) \
        if len(ts) > 1 else float("nan")
    deltas = [abs(got[s] - want[s]) / max(abs(want[s]), 1e-12)
              for s in want if s in got]
    loss_delta = max(deltas) if deltas else float("nan")

    return {"metric": "elastic_detect_resume_s",
            "value": round(detect_resume_s, 3),
            "unit": "s",
            "drill_rc": [rc0, rc1],
            "loss_continuation_max_rel_delta": loss_delta,
            "async_save_overhead_pct": round(async_pct, 2),
            "sync_save_overhead_pct": round(sync_pct, 2),
            "async_overhead_bar_pct": 5.0,
            "baseline_step_ms": round(base * 1e3, 4),
            "async_step_ms": round(t_async * 1e3, 4),
            "sync_step_ms": round(t_sync * 1e3, 4),
            "save_every": save_every,
            "train_steps": train_steps}


def bench_ps_ha(n_rows=4096, dim=32, batch=64, lat_pushes=150,
                stream_pushes=200, seed=0):
    """BENCH_CONFIG=ps_ha (docs/PS_HA.md): the economics of the PS
    high-availability plane. Three numbers:

    - failover recovery — kill the primary under a live group client,
      promote the hot standby (epoch-fenced), and time kill -> first
      successful push; versus the pre-HA baseline of
      restart_from_snapshot on the same seeded table (bar: promotion
      wins — the standby already holds the rows);
    - semi-sync ack tax — p50 push latency with
      PADDLE_PS_HA_SEMISYNC=1 vs async replication on an identical
      pair (bar: <150% — the ack is one replication round-trip
      overlapped outside the commit scope, so at most ~one extra
      loopback RTT on top of the push RTT);
    - steady-state replication lag under a wide&deep-style stream
      (4 slot tables, 80/20 hot/uniform id batches), sampled per push
      from the hub's per-peer feeds, plus the drain-to-caught-up time
      once the stream stops."""
    import shutil
    import tempfile

    from paddle_tpu.distributed.fleet.runtime.parameter_server_runtime \
        import PSClient, PSServer
    from paddle_tpu.distributed.fleet.runtime.ps_ha import promote_best

    root = tempfile.mkdtemp(prefix="bench_ps_ha_")
    rng = np.random.RandomState(seed)
    rows = rng.randn(n_rows, dim).astype(np.float32)

    def wait_for(cond, timeout=30.0, what="condition"):
        deadline = time.perf_counter() + timeout
        while not cond():
            if time.perf_counter() > deadline:
                raise TimeoutError(f"ps_ha bench: timed out on {what}")
            time.sleep(0.002)

    def pair(tag, semisync=None):
        env = {} if semisync is None else {
            "PADDLE_PS_HA_SEMISYNC": str(semisync),
            "PADDLE_PS_HA_SEMISYNC_TIMEOUT": "10.0"}
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            prim = PSServer(
                "127.0.0.1:0", wal=True,
                snapshot_dir=os.path.join(root, tag, "p"))
            prim.serve_in_thread()
            stby = PSServer(
                "127.0.0.1:0", wal=True, primary=prim.endpoint,
                snapshot_dir=os.path.join(root, tag, "s"))
            stby.serve_in_thread()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        wait_for(lambda: stby._ha_replicator.synced.is_set(),
                 what=f"{tag} standby bootstrap")
        return prim, stby

    def stop(*servers):
        for s in servers:
            try:
                s.shutdown()
                s.server_close()
            except Exception:
                pass

    def seed_table(cl, name):
        for lo in range(0, n_rows, 256):
            ids = np.arange(lo, min(lo + 256, n_rows))
            cl.push(name, dim, ids, rows[ids])

    def push_p50(cl, name):
        lats = []
        for _ in range(lat_pushes):
            ids = np.unique(rng.randint(0, n_rows, batch))
            vals = rng.randn(len(ids), dim).astype(np.float32)
            t0 = time.perf_counter()
            cl.push(name, dim, ids, vals)
            lats.append(time.perf_counter() - t0)
        lats.sort()
        return lats[len(lats) // 2]

    try:
        # -- semi-sync ack tax: identical pairs, async vs K=1 ---------
        prim_a, stby_a = pair("async")
        cl_a = PSClient([prim_a.endpoint])
        seed_table(cl_a, "emb")
        push_p50(cl_a, "emb")  # warm
        async_p50 = push_p50(cl_a, "emb")

        prim_s, stby_s = pair("semi", semisync=1)
        cl_s = PSClient([prim_s.endpoint])
        seed_table(cl_s, "emb")
        push_p50(cl_s, "emb")  # warm
        semi_p50 = push_p50(cl_s, "emb")
        semi_degraded = int(prim_s._ha.degraded)
        cl_s.close()
        stop(stby_s, prim_s)

        # -- steady-state replication lag under wide&deep-style load --
        hot = rng.randint(0, n_rows, 1024)
        lag_samples = []
        t0 = time.perf_counter()
        for i in range(stream_pushes):
            if rng.rand() < 0.8:
                ids = np.unique(hot[rng.randint(0, len(hot), batch)])
            else:
                ids = np.unique(rng.randint(0, n_rows, batch))
            vals = rng.randn(len(ids), dim).astype(np.float32)
            cl_a.push(f"slot{i % 4}", dim, ids, vals)
            st = prim_a._ha.status()
            if st:
                lag_samples.append(st[0]["lag_rows"])
        stream_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        wait_for(lambda: all(f["lag_rows"] == 0
                             for f in prim_a._ha.status()),
                 what="replication drain")
        drain_s = time.perf_counter() - t0

        # -- failover: kill primary, promote, first push lands --------
        grp = PSClient([prim_a.endpoint + "|" + stby_a.endpoint])
        probe_ids = np.arange(8)
        probe = np.ones((8, dim), np.float32)
        grp.push("emb", dim, probe_ids, probe)
        wait_for(lambda: (stby_a._ha_replicator.applied_seq
                          >= prim_a._ha.seq),
                 what="standby caught up pre-kill")
        t0 = time.perf_counter()
        prim_a.kill()
        new_prim = promote_best([stby_a.endpoint], 2, timeout=10.0)
        grp.push("emb", dim, probe_ids, probe)
        failover_s = time.perf_counter() - t0
        grp.close()
        cl_a.close()
        stop(stby_a)

        # -- pre-HA baseline: snapshot-respawn on the same endpoint.
        # A real respawn is a fresh PROCESS (launcher child) that
        # restores snapshot+WAL before serving, so the baseline spawns
        # the killable-server fixture, not an in-process restart.
        import subprocess
        solo_dir = os.path.join(root, "solo")
        srv = PSServer("127.0.0.1:0", wal=True, snapshot_dir=solo_dir)
        srv.serve_in_thread()
        cl = PSClient([srv.endpoint])
        seed_table(cl, "emb")
        ep = srv.endpoint
        srv.kill()
        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ, PS_ENDPOINT=ep, PADDLE_PS_WAL="1",
                   PADDLE_PS_SNAPSHOT_DIR=solo_dir,
                   JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = repo + os.pathsep + env.get(
            "PYTHONPATH", "")
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(repo, "tests", "fixtures",
                          "ps_fault_server.py")],
            env=env, stdout=subprocess.PIPE, text=True)
        try:
            proc.stdout.readline()  # READY line: restored + serving
            cl.push("emb", dim, probe_ids, probe)
            respawn_s = time.perf_counter() - t0
        finally:
            proc.kill()
            proc.wait()
        cl.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    overhead_pct = ((semi_p50 - async_p50) / async_p50 * 100
                    if async_p50 > 0 else 0.0)
    return {"metric": "ps_ha_failover_first_push_s",
            "value": round(failover_s, 4),
            "unit": "s",
            "respawn_first_push_s": round(respawn_s, 4),
            "promotion_beats_respawn": bool(failover_s < respawn_s),
            "promoted_ok": bool(new_prim is not None),
            "async_push_p50_ms": round(async_p50 * 1e3, 4),
            "semisync_push_p50_ms": round(semi_p50 * 1e3, 4),
            "semisync_overhead_pct": round(overhead_pct, 2),
            "semisync_overhead_bar_pct": 150.0,
            "semisync_bar_ok": bool(overhead_pct <= 150.0),
            "semisync_degraded_acks": semi_degraded,
            "stream_lag_rows_mean": round(
                float(np.mean(lag_samples)), 2) if lag_samples
            else float("nan"),
            "stream_lag_rows_max": int(max(lag_samples))
            if lag_samples else -1,
            "stream_push_per_s": round(stream_pushes / stream_s, 1),
            "lag_drain_s": round(drain_s, 4),
            "rows": n_rows, "dim": dim, "batch": batch,
            "lat_pushes": lat_pushes, "stream_pushes": stream_pushes}


def bench_tiered(vocab=1 << 26, dim=8, batch=256, train_steps=400,
                 serve_steps=400, warm_budget=256 * 1024, seed=0):
    """BENCH_CONFIG=tiered (docs/PS_TIERED.md): widedeep-style
    training + serving against a 2^26-row embedding vocab on a tiered
    parameter server whose warm budget is a tiny fraction of the
    touched bytes. Ids follow a zipf(1.2) skew, so the hot head lives
    warm and the long tail demand-pages from the chunk store.

    Headline = serving-phase p99 pull latency (the SLO number a
    lookup service sees when the tail faults cold rows in). Also
    records per-tier hit rates, demotion counts, warm residency vs
    budget after a drain, and client-observed cold-fault totals."""
    import shutil
    import tempfile

    from paddle_tpu.distributed.fleet.runtime.parameter_server_runtime \
        import PSClient, PSServer

    root = tempfile.mkdtemp(prefix="bench_tiered_")
    rng = np.random.default_rng(seed)
    try:
        srv = PSServer("127.0.0.1:0", wal=True,
                       snapshot_dir=os.path.join(root, "snap"),
                       tier_warm_bytes=warm_budget,
                       tier_store_dir=os.path.join(root, "store"))
        srv.serve_in_thread()
        cl = PSClient([srv.endpoint])

        def ids_for(step):
            # zipf rank -> id directly: rank 1 is the hottest row and
            # stays hot across steps, so the head settles warm while
            # the tail keeps faulting from the chunk store.
            return (rng.zipf(1.2, batch).astype(np.int64) - 1) % vocab

        # -- train: pull + push per step ------------------------------
        t0 = time.perf_counter()
        for step in range(train_steps):
            ids = ids_for(step)
            v = cl.pull("emb", dim, ids)
            cl.push("emb", dim, ids, 0.01 * v)
        train_s = time.perf_counter() - t0
        train_faults = cl.cold_faults

        # -- serve: pulls only, timed per call ------------------------
        lats = []
        for step in range(serve_steps):
            ids = ids_for(train_steps + step)
            t1 = time.perf_counter()
            cl.pull("emb", dim, ids)
            lats.append(time.perf_counter() - t1)
        serve_faults = cl.cold_faults - train_faults

        t = srv.tables["emb"]
        t.drain()
        st = t.stats()
        warm_after_drain = t.warm_resident_bytes()
        touched = st["warm_rows"] + st["cold_rows"]
        lookups = st["warm_hits"] + st["cold_faults"]
        hit_warm = (st["warm_hits"] / lookups) if lookups else 0.0
        cl.close()
        srv.kill()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    lats.sort()
    p50 = lats[len(lats) // 2]
    p99 = lats[min(int(len(lats) * 0.99), len(lats) - 1)]
    steps_s = (train_steps + serve_steps) / (
        train_s + sum(lats)) if lats else 0.0
    return {"metric": "ps_tier_serve_pull_p99_ms",
            "value": round(p99 * 1e3, 4),
            "unit": "ms",
            "serve_pull_p50_ms": round(p50 * 1e3, 4),
            "train_examples_per_s": round(
                train_steps * batch / train_s, 1),
            "steps_per_s": round(steps_s, 1),
            "vocab_rows": vocab,
            "touched_rows": touched,
            "warm_budget_bytes": warm_budget,
            "warm_resident_bytes": warm_after_drain,
            "warm_under_budget": bool(warm_after_drain <= warm_budget),
            "warm_hit_rate": round(hit_warm, 4),
            "cold_fault_rate": round(1.0 - hit_warm, 4),
            "warm_rows": st["warm_rows"],
            "cold_rows": st["cold_rows"],
            "segments": st["segments"],
            "demoted_clean": st["demoted_clean"],
            "demoted_flush": st["demoted_flush"],
            "cold_read_errors": st["cold_read_errors"],
            "client_cold_faults_train": int(train_faults),
            "client_cold_faults_serve": int(serve_faults),
            "dim": dim, "batch": batch,
            "train_steps": train_steps, "serve_steps": serve_steps}


def bench_infer_latency(batch=1, seq=128, steps=30, warmup=5):
    """BERT-base inference latency through the Predictor (analysis
    predictor parity path): save -> load -> timed ZeroCopyRun.

    Headline = steady-state per-inference latency via the zero-copy
    handle API (outputs device-side, one host sync at the end) — the
    number a pipelined serving loop sees. ``blocked_ms`` additionally
    reports single-shot run-to-numpy latency; on this image's tunneled
    TPU runtime that includes one relay round-trip (~100 ms) charged to
    ANY blocked host read after the first D2H in the process (see README
    "runtime notes"), so it measures the transport, not the model."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, Predictor
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.static import InputSpec

    cfg = BertConfig.base()
    model = BertForPretraining(cfg)
    model.eval()
    d = tempfile.mkdtemp()
    try:
        paddle.jit.save(model, d,
                        input_spec=[InputSpec([-1, seq], "int64", "ids")])
        c = Config(model_dir=d)
        c.enable_bf16()
        pred = Predictor(c)
        ids = np.random.RandomState(0).randint(
            4, cfg.vocab_size, (batch, seq)).astype("int64")
        in_h = pred.get_input_handle(pred.get_input_names()[0])
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        in_h.copy_from_cpu(ids)
        for _ in range(warmup):
            pred.run()
        _sync(out_h._value)  # also compiles the tiny sync-slice program
        # steady-state: chain zero-copy runs, one sync at the end
        t0 = time.perf_counter()
        for _ in range(steps):
            pred.run()
        _sync(out_h._value)
        loop = time.perf_counter() - t0
        # the loop's closing _sync is ~1 relay RTT of transport, not model
        # time — measure it idle (queue empty) and charge it once, not
        # once-per-step
        t0 = time.perf_counter()
        _sync(out_h._value)
        rtt = time.perf_counter() - t0
        dt = max(loop - rtt, loop * 0.5) / steps
        # single-shot blocked (run + fetch to numpy each call)
        t0 = time.perf_counter()
        for _ in range(3):
            pred.run()
            _ = out_h.copy_to_cpu()
        blocked = (time.perf_counter() - t0) / 3
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return {"metric": "bert_base_infer_latency_ms",
            "value": round(dt * 1e3, 3), "unit": "ms", "batch": batch,
            "seq": seq, "blocked_ms": round(blocked * 1e3, 3),
            "sync_rtt_ms": round(rtt * 1e3, 3),
            "note": "zero-copy steady-state (final-sync RTT charged once, "
                    "not per step); blocked_ms includes tunnel RTT + full "
                    "output transfer per call (runtime, not model)"}


def bench_allreduce(mb=64, steps=30, warmup=5):
    """Achieved allreduce bandwidth over the device mesh (BASELINE config 2
    companion metric). Algorithmic bandwidth: 2·(n-1)/n · bytes / time."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices())
    n = len(devs)
    mesh = Mesh(devs, ("dp",))
    nbytes = mb * 1024 * 1024
    x = jnp.zeros((n, nbytes // 4), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("dp")))

    @jax.jit
    def allreduce(x):
        from jax.experimental.shard_map import shard_map
        return shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P("dp"))(x)

    for _ in range(warmup):
        out = allreduce(x)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = allreduce(out)
    _sync(out)
    dt = (time.perf_counter() - t0) / steps
    bw = 2 * (n - 1) / max(n, 1) * nbytes / dt / 1e9
    return {"metric": "allreduce_algbw_gbps", "value": round(bw, 2),
            "unit": "GB/s", "devices": n, "payload_mb": mb}


def bench_kernels(reps=5):
    """BENCH_CONFIG=kernels: per-kernel fused-vs-unfused speedups at
    model shapes (the PR-7 epilogue-fused decoder sub-blocks + the
    pre-existing fused FFN/LN kernels) plus tuning-cache COLD vs WARM
    first-call latency — the number a serving fleet saves per replica
    by shipping a pre-warmed PADDLE_TPU_AUTOBENCH_CACHE. On TPU the
    shapes are the gpt_350m / bert_base_512 hot shapes; off-TPU the
    kernels run tiny interpret-mode shapes (plumbing proof, timings not
    meaningful) so the record exists every round."""
    import tempfile

    import jax
    from paddle_tpu.ops import autobench
    from paddle_tpu.ops import pallas_block, pallas_ffn, pallas_layer_norm
    from paddle_tpu.ops.pallas_attention import on_tpu

    tpu = on_tpu()
    saved_interp = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
    if not tpu:
        os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    if tpu:
        dt = "bfloat16"
        gates = {
            "out_ln_bert512":
                pallas_block._gate_out_ln(8192, 768, 768, dt),
            "ffn_block_bert512":
                pallas_block._gate_ffn_ln(8192, 768, 3072, dt, "gelu",
                                          "post"),
            "out_ln_gpt350m":
                pallas_block._gate_out_ln(8192, 1024, 1024, dt),
            "ffn_block_gpt350m":
                pallas_block._gate_ffn_ln(8192, 1024, 4096, dt,
                                          "gelu_tanh", "none"),
            "ffn_bert512": pallas_ffn._gate_ffn(8192, 768, 3072, dt),
            "layer_norm_bert512":
                pallas_layer_norm._gate_ln(8192, 768, dt),
        }
    else:
        dt = "float32"
        gates = {
            "out_ln_tiny": pallas_block._gate_out_ln(128, 128, 128, dt),
            "ffn_block_tiny":
                pallas_block._gate_ffn_ln(128, 128, 256, dt, "gelu",
                                          "none"),
        }
    kernels = {}
    speedups = []
    for name, (key, cands, make_args) in gates.items():
        t = {}
        for cname, fn in cands.items():
            try:
                t[cname] = autobench._measure(fn, make_args, reps)
            except Exception as e:
                t[cname] = None
                kernels.setdefault("errors", {})[f"{name}/{cname}"] = \
                    f"{type(e).__name__}: {e}"
        rec = {c: (round(v * 1e3, 3) if v else None)
               for c, v in t.items()}
        if t.get("pallas") and t.get("xla"):
            rec["speedup_fused"] = round(t["xla"] / t["pallas"], 3)
            speedups.append(rec["speedup_fused"])
        kernels[name] = rec

    # tuning-cache cold vs warm first-call latency: cold pays the
    # measuring round; warm (a "restarted replica") adopts from disk.
    # Pre-existing cache/interpret env is restored afterwards — an
    # operator's real fleet cache must survive a bench run.
    saved_cache = os.environ.get("PADDLE_TPU_AUTOBENCH_CACHE")
    with tempfile.TemporaryDirectory() as d:
        os.environ["PADDLE_TPU_AUTOBENCH_CACHE"] = \
            os.path.join(d, "autobench.json")
        try:
            import jax.numpy as jnp
            cands = {"a": lambda x: jnp.tanh(x) @ x,
                     "b": lambda x: x @ x}
            mk = lambda: (jnp.ones((256, 256), jnp.float32),)
            autobench.clear()
            t0 = time.perf_counter()
            autobench.prefer(("bench_cache_probe",), cands, mk, reps=3)
            cold = time.perf_counter() - t0
            autobench.clear()  # new-process simulation; file survives
            t0 = time.perf_counter()
            autobench.prefer(("bench_cache_probe",), cands, mk, reps=3)
            warm = time.perf_counter() - t0
            warm_stats = autobench.stats()
        finally:
            if saved_cache is None:
                del os.environ["PADDLE_TPU_AUTOBENCH_CACHE"]
            else:
                os.environ["PADDLE_TPU_AUTOBENCH_CACHE"] = saved_cache
            if not tpu:
                if saved_interp is None:
                    os.environ.pop("PADDLE_TPU_PALLAS_INTERPRET", None)
                else:
                    os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = \
                        saved_interp
            autobench.clear()
    geo = float(np.exp(np.mean(np.log(speedups)))) if speedups else None
    return {"metric": "kernels_fused_geomean_speedup",
            "value": round(geo, 3) if geo else None,
            "unit": "x_vs_composed_xla",
            "on_tpu": tpu, "kernels": kernels,
            "cache": {"cold_first_call_ms": round(cold * 1e3, 2),
                      "warm_first_call_ms": round(warm * 1e3, 2),
                      "warm_measures": warm_stats["measures"],
                      "warm_hits": warm_stats["cache_hits"]},
            "device_kind": str(jax.devices()[0].device_kind)}


def bench_tsdb(steps=200, hidden=256, layers=4, heads=4, slots=4,
               seed=0, ingest_batches=2500, query_reps=50):
    """Time-series-plane cost guardrail (ISSUE 18 acceptance): a LIVE
    agent streams to a collector whose TSDB + alert evaluator are
    toggled A/B/A on the same engine — the toggle isolates the
    history/alerting cost ON TOP of fleet telemetry (agent stays armed
    both ways), and all TSDB writes ride the collector's server
    threads, so the decode hot path sees the same <2% bar as the other
    observability toggles. Supplementary stats measure the plane
    itself against a disk-backed store: batch ingest rate, bytes per
    sample on disk after block sealing + downsampling, and query
    latency for range/rate/quantile over the ingested history."""
    import shutil
    import tempfile

    from paddle_tpu.observability import agent as tel_agent
    from paddle_tpu.observability.collector import (CollectorServer,
                                                    TelemetryCollector)
    from paddle_tpu.observability.timeseries import TimeSeriesDB

    col = TelemetryCollector(tsdb=TimeSeriesDB())
    srv = CollectorServer("127.0.0.1:0", collector=col).start()
    paused = []

    def set_enabled(on):
        # ingest() reads tsdb/alerts without holding the collector
        # lock, so the swap is a plain attribute flip
        if on:
            if paused:
                col.tsdb, col.alerts = paused.pop()
        else:
            paused.append((col.tsdb, col.alerts))
            col.tsdb = col.alerts = None

    tel_agent.arm(srv.endpoint)
    try:
        rec = _bench_serving_toggle_overhead(
            set_enabled, "serving_tsdb_overhead_pct", steps=steps,
            hidden=hidden, layers=layers, heads=heads, slots=slots,
            seed=seed)
    finally:
        tel_agent.disarm()
        srv.stop()

    # -- plane economics: a dedicated disk-backed store, block size
    # shrunk so sealing + downsampling actually fire inside the bench
    root = tempfile.mkdtemp(prefix="tsdb_bench_")
    try:
        db = TimeSeriesDB(dir_=os.path.join(root, "tsdb"),
                          block_bytes=256 * 1024,
                          retention_bytes=8 * 2**20)
        hist_buckets = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5)
        base_t = 1_700_000_000.0
        t0 = time.perf_counter()
        appended = 0
        for i in range(ingest_batches):
            # 1s cadence over ~40min of history: crosses the raw
            # window (900s) so mid-resolution downsampling is exercised
            t = base_t + i
            entries = [("bench_counter_total",
                        {"host": "h", "pid": str(p), "role": "trainer"},
                        "counter", float(i * 10 + p), None)
                       for p in range(8)]
            entries += [("bench_gauge",
                         {"host": "h", "pid": str(p),
                          "role": "trainer"},
                         "gauge", float((i + p) % 97), None)
                        for p in range(8)]
            cum = tuple(min(i + 1, (b + 1) * (i + 1) // 7 + 1)
                        for b in range(len(hist_buckets) + 1))
            entries.append(("bench_latency_seconds",
                            {"host": "h", "pid": "0",
                             "role": "trainer"},
                            "histogram",
                            (cum, 0.01 * (i + 1), float(cum[-1])),
                            hist_buckets))
            appended += db.append(t, entries)
        ingest_s = time.perf_counter() - t0
        st = db.stats()
        end_t = base_t + ingest_batches - 1

        def timeit(fn):
            q0 = time.perf_counter()
            for _ in range(query_reps):
                fn()
            return (time.perf_counter() - q0) / query_reps * 1e3

        q_range = timeit(lambda: db.range(
            "bench_gauge", start=end_t - 300, end=end_t))
        q_rate = timeit(lambda: db.rate(
            "bench_counter_total", 300, at=end_t))
        q_quantile = timeit(lambda: db.quantile(
            "bench_latency_seconds", 0.99, 300, at=end_t))
        db.close()
        rec["tsdb"] = {
            "ingest_samples_per_s": round(appended / ingest_s),
            "samples": appended,
            "series": st["series"],
            "bytes_on_disk": st["bytes_on_disk"],
            "bytes_per_sample": round(
                st["bytes_on_disk"] / max(1, appended), 2),
            "blocks_sealed": st["counts"]["sealed"],
            "blocks_compacted": st["counts"]["compacted"],
            "blocks_deleted": st["counts"]["deleted"],
            "query_ms": {"range_5m": round(q_range, 3),
                         "rate_5m": round(q_rate, 3),
                         "quantile_p99_5m": round(q_quantile, 3)},
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return rec


def main():
    which = os.environ.get("BENCH_CONFIG", "bert_base")
    if which == "lenet":
        rec = bench_lenet()
    elif which == "bert_tiny":
        rec = bench_bert("tiny", batch=8, seq=64)
    elif which == "bert_base_512":
        rec = bench_bert("base_512", batch=16, seq=512, steps=24)
    elif which == "flash_attn":
        rec = bench_flash_attn()
    elif which == "allreduce":
        rec = bench_allreduce()
    elif which == "gpt":
        rec = bench_gpt()
    elif which == "resnet50":
        rec = bench_resnet50()
    elif which == "widedeep":
        rec = bench_widedeep()
    elif which == "infer":
        rec = bench_infer_latency()
    elif which == "serving":
        rec = bench_serving()
    elif which == "slo":
        rec = bench_slo()
    elif which == "prefix":
        rec = bench_prefix()
    elif which == "chaos":
        rec = bench_chaos()
    elif which == "router":
        rec = bench_router()
    elif which == "metrics_overhead":
        rec = bench_metrics_overhead()
    elif which == "flight_overhead":
        rec = bench_flight_overhead()
    elif which == "telemetry_overhead":
        rec = bench_telemetry_overhead()
    elif which == "perfwatch_overhead":
        rec = bench_perfwatch_overhead()
    elif which == "checkpoint":
        rec = bench_checkpoint()
    elif which == "elastic":
        rec = bench_elastic()
    elif which == "gpt_1p3b":
        rec = bench_gpt_1p3b()
    elif which == "kernels":
        rec = bench_kernels()
    elif which == "transport":
        rec = bench_transport()
    elif which == "online":
        rec = bench_online()
    elif which == "ps_ha":
        rec = bench_ps_ha()
    elif which == "tiered":
        rec = bench_tiered()
    elif which == "tsdb":
        rec = bench_tsdb()
    else:
        # batch 64 wins on v5e since the rbg-PRNG switch removed the
        # dropout-mask cost (32.5% MFU vs 31.8% at batch 32; pre-rbg,
        # batch 64 regressed)
        rec = bench_bert("base", batch=64)
        # secondary configs ride along in the single JSON line so every
        # round's BENCH record carries the whole BASELINE matrix
        if os.environ.get("BENCH_EXTRAS", "1") != "0":
            extras = {}
            # (name, full-steps runner, reduced-steps runner). Every config
            # records EVERY round (the r4 verdict's completeness bar): the
            # order rotates by round (round index = count of committed
            # BENCH_r*.json records) so no config is systematically
            # starved, and when the budget runs out configs drop to a
            # minimal 2-step run rather than skipping — 2 steps still
            # records a real number.
            configs = [
                ("widedeep",
                 lambda: bench_widedeep(steps=10, warmup=2),
                 # reduced mode keeps ONE small run through the real
                 # TCP transport so ps_tcp always lands in the record
                 lambda: (os.environ.__setitem__(
                     "BENCH_WIDEDEEP_PS", "min"),
                     bench_widedeep(steps=2, warmup=1))[1]),
                ("infer_latency",
                 lambda: bench_infer_latency(steps=15, warmup=3),
                 lambda: bench_infer_latency(steps=5, warmup=1)),
                ("serving",
                 lambda: bench_serving(),
                 lambda: bench_serving(num_requests=12, hidden=256,
                                       layers=4, heads=4, max_new=32)),
                ("flash_attn", bench_flash_attn,
                 lambda: bench_flash_attn(steps=6, warmup=1)),
                ("resnet50",
                 lambda: bench_resnet50(steps=8, warmup=2),
                 lambda: bench_resnet50(steps=2, warmup=1)),
                ("bert_base_512",
                 lambda: bench_bert("base_512", batch=16, seq=512,
                                    steps=16, warmup=2),
                 lambda: bench_bert("base_512", batch=16, seq=512,
                                    steps=2, warmup=1)),
                ("gpt_350m",
                 lambda: bench_gpt(steps=6, warmup=2),
                 lambda: bench_gpt(steps=2, warmup=1)),
                ("gpt_1p3b",
                 lambda: bench_gpt_1p3b(steps=4, warmup=1),
                 lambda: bench_gpt_1p3b(steps=2, warmup=1)),
            ]
            try:
                import glob as _glob
                rnd = len(_glob.glob(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_r*.json")))
            except Exception:
                rnd = 0
            configs = configs[rnd % len(configs):] \
                + configs[:rnd % len(configs)]
            budget = float(os.environ.get("BENCH_EXTRAS_BUDGET", 420))
            for i, (name, full, reduced) in enumerate(configs):
                # wall budget so the driver's bench window is never blown
                # badly (each config costs a fresh XLA compile ~20-40s);
                # share the remaining budget across the configs still
                # queued and shrink step counts rather than skipping
                left = budget - (time.perf_counter() - _T0)
                share = left / (len(configs) - i)
                try:
                    # a full config costs ~25s compile + ~15s steps; run
                    # full whenever the fair share covers that, reduced
                    # otherwise (reduced still records a real number)
                    extras[name] = full() if share > 45 else reduced()
                except Exception as e:  # keep the headline robust
                    extras[name] = {"error": f"{type(e).__name__}: {e}"}
            import jax
            if len(jax.devices()) > 1:
                try:
                    extras["allreduce"] = bench_allreduce()
                except Exception as e:
                    extras["allreduce"] = {
                        "error": f"{type(e).__name__}: {e}"}
            rec["extras"] = extras
    rec.setdefault("vs_baseline", 1.0)
    # every config leaves a schema-versioned record; the same writer
    # backs `perfwatch record`, and PADDLE_TPU_BENCH_OUT collects a
    # sweep into one JSONL artifact for `perfwatch compare`
    from paddle_tpu.observability.perfwatch import finalize_record
    finalize_record(rec, which)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
