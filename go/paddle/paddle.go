// Package paddle — Go inference/training binding over the paddle_tpu C
// ABI (reference go/paddle/{config,predictor,tensor}.go over
// paddle_fluid_c; here one file over libpaddle_tpu_capi).
//
// Build: the cgo directives expect the header dir and library path via
//   CGO_CFLAGS="-I<repo>/paddle_tpu/capi"
//   CGO_LDFLAGS="-L<repo>/paddle_tpu/capi/build -lpaddle_tpu_capi \
//                -Wl,-rpath,<repo>/paddle_tpu/capi/build"
// (tests/test_capi.py sets these when a Go toolchain is present).
package paddle

// #include <stdlib.h>
// #include <stdint.h>
// #include "paddle_c_api.h"
import "C"

import (
	"fmt"
	"unsafe"
)

// DataType mirrors PD_DataType.
type DataType int

const (
	Float32 DataType = iota
	Int32
	Int64
)

func dtypeSize(t DataType) int {
	if t == Int64 {
		return 8
	}
	return 4
}

// Tensor is a dense array handed to / received from the runtime.
type Tensor struct {
	Shape []int64
	Dtype DataType
	Data  []byte // raw little-endian buffer, len = numel * dtype size
}

func (t *Tensor) numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}

func toC(t *Tensor, c *C.PD_Tensor) error {
	if len(t.Shape) > 8 {
		return fmt.Errorf("paddle: ndim %d > 8", len(t.Shape))
	}
	if int64(len(t.Data)) != t.numel()*int64(dtypeSize(t.Dtype)) {
		return fmt.Errorf("paddle: data length %d != numel*itemsize",
			len(t.Data))
	}
	c.data = unsafe.Pointer(&t.Data[0])
	c.ndim = C.int(len(t.Shape))
	c.dtype = C.PD_DataType(t.Dtype)
	for i, d := range t.Shape {
		c.shape[i] = C.int64_t(d)
	}
	return nil
}

func fromC(c *C.PD_Tensor) Tensor {
	var t Tensor
	t.Dtype = DataType(c.dtype)
	n := int64(1)
	for i := 0; i < int(c.ndim); i++ {
		d := int64(c.shape[i])
		t.Shape = append(t.Shape, d)
		n *= d
	}
	size := n * int64(dtypeSize(t.Dtype))
	t.Data = C.GoBytes(unsafe.Pointer(c.data), C.int(size))
	return t
}

func lastError() error {
	return fmt.Errorf("paddle: %s", C.GoString(C.PD_GetLastError()))
}

// Predictor wraps PD_Predictor (an exported inference model dir).
type Predictor struct {
	p *C.PD_Predictor
}

func NewPredictor(modelDir string) (*Predictor, error) {
	cs := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cs))
	p := C.PD_NewPredictor(cs)
	if p == nil {
		return nil, lastError()
	}
	return &Predictor{p: p}, nil
}

func (p *Predictor) Delete() { C.PD_DeletePredictor(p.p) }

func (p *Predictor) InputNum() int  { return int(C.PD_GetInputNum(p.p)) }
func (p *Predictor) OutputNum() int { return int(C.PD_GetOutputNum(p.p)) }

// Run executes the model on the inputs (model feed order).
func (p *Predictor) Run(inputs []Tensor) ([]Tensor, error) {
	cin := make([]C.PD_Tensor, len(inputs))
	for i := range inputs {
		if err := toC(&inputs[i], &cin[i]); err != nil {
			return nil, err
		}
	}
	nOut := p.OutputNum()
	if nOut < 0 {
		return nil, lastError()
	}
	cout := make([]C.PD_Tensor, nOut)
	var inPtr *C.PD_Tensor
	if len(cin) > 0 {
		inPtr = &cin[0]
	}
	var outPtr *C.PD_Tensor
	if len(cout) > 0 {
		outPtr = &cout[0]
	}
	if C.PD_PredictorRun(p.p, inPtr, C.int(len(cin)), outPtr,
		C.int(nOut)) != 0 {
		return nil, lastError()
	}
	outs := make([]Tensor, nOut)
	for i := range cout {
		outs[i] = fromC(&cout[i])
	}
	return outs, nil
}

// Trainer wraps PD_Trainer (a fluid.io.save_train_model dir) — the
// language-free training loop (reference train/demo_trainer.cc).
type Trainer struct {
	t *C.PD_Trainer
}

func NewTrainer(modelDir string) (*Trainer, error) {
	cs := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cs))
	t := C.PD_NewTrainer(cs)
	if t == nil {
		return nil, lastError()
	}
	return &Trainer{t: t}, nil
}

func (t *Trainer) Delete()      { C.PD_DeleteTrainer(t.t) }
func (t *Trainer) FeedNum() int { return int(C.PD_TrainerFeedNum(t.t)) }

// Run performs one optimizer step and returns the loss.
func (t *Trainer) Run(feeds []Tensor) (float32, error) {
	cin := make([]C.PD_Tensor, len(feeds))
	for i := range feeds {
		if err := toC(&feeds[i], &cin[i]); err != nil {
			return 0, err
		}
	}
	var loss C.float
	var inPtr *C.PD_Tensor
	if len(cin) > 0 {
		inPtr = &cin[0]
	}
	if C.PD_TrainerRun(t.t, inPtr, C.int(len(cin)), &loss) != 0 {
		return 0, lastError()
	}
	return float32(loss), nil
}

// Save persists the trained parameters.
func (t *Trainer) Save(dir string) error {
	cs := C.CString(dir)
	defer C.free(unsafe.Pointer(cs))
	if C.PD_TrainerSave(t.t, cs) != 0 {
		return lastError()
	}
	return nil
}
