module paddle_tpu/go/paddle

go 1.20
